"""Elastic-traffic-plane tests (``d4pg_tpu/elastic``).

The acceptance set for the scaling plane: seeded traffic-model
determinism (two models from one config, bit-identical traces), the
flash-crowd and heavy-tail shape pins, the class-aware admission
policy's no-priority-inversion math, autoscaler hysteresis + cooldown
on a scripted signal stream, the scaling-ledger replay oracle (and its
tamper sensitivity), the live capacity setters the autoscaler drives,
and the bench-artifact elastic schema gate over the committed A/B
drill — the artifact where autoscaler-on must beat static on BOTH
serving SLO breaches and ingest shed rows at equal seeded offered
load.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np
import pytest

from d4pg_tpu.elastic.admission import AdmissionPolicy
from d4pg_tpu.elastic.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    ControlPolicy,
    extract_signals,
    replay_matches,
)
from d4pg_tpu.elastic.ledger import ScalingLedger, canonical_record
from d4pg_tpu.elastic.traffic import TrafficConfig, TrafficModel

pytestmark = pytest.mark.elastic

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- seeded traffic model ---------------------------------------------------

def test_traffic_model_deterministic():
    """Two models built from the same config emit bit-identical offered
    load — per lane and fleet-summed — and a different seed does not
    (the replay contract the A/B drill's equal-offered-load claim
    stands on)."""
    cfg = TrafficConfig(seed=7, n_actors=6, diurnal_amp=0.3,
                        flash_rate_per_s=0.5, horizon_s=30.0)
    a, b = TrafficModel(cfg), TrafficModel(cfg)
    for lane in range(cfg.n_actors):
        assert np.array_equal(a.trace(lane, 20.0, 0.1),
                              b.trace(lane, 20.0, 0.1))
    assert np.array_equal(a.fleet_trace(20.0, 0.1), b.fleet_trace(20.0, 0.1))
    assert a.flash_events() == b.flash_events()
    other = TrafficModel(TrafficConfig(seed=8, n_actors=6, diurnal_amp=0.3,
                                       flash_rate_per_s=0.5, horizon_s=30.0))
    assert not np.array_equal(a.fleet_trace(20.0, 0.1),
                              other.fleet_trace(20.0, 0.1))


def test_flash_crowd_shape():
    """A scripted crowd multiplies the rate by its amplitude exactly
    while active and leaves it untouched outside; overlapping crowds
    take the max, not the product."""
    cfg = TrafficConfig(seed=0, n_actors=1, diurnal_amp=0.0,
                        pareto_alpha=1e9,  # weight -> 1: isolate the flash
                        flash_schedule=((2.0, 1.0, 6.0), (2.5, 1.0, 4.0)))
    m = TrafficModel(cfg)
    base = m.rate(0, 0.0)
    assert base == pytest.approx(cfg.base_rows_per_sec, rel=1e-6)
    assert m.rate(0, 2.4) == pytest.approx(6.0 * base)
    assert m.rate(0, 2.7) == pytest.approx(6.0 * base)  # overlap: max(6,4)
    assert m.rate(0, 3.2) == pytest.approx(4.0 * base)  # first crowd over
    assert m.rate(0, 4.0) == pytest.approx(base)        # both over


def test_pareto_tail_and_floor():
    """The per-actor weights are a normalized heavy tail: fleet mean
    pinned at 1.0 (offered fleet load independent of the draw), a few
    hot lanes well above the median, and the rate floor holds through
    the deepest diurnal trough."""
    cfg = TrafficConfig(seed=3, n_actors=256, pareto_alpha=1.5)
    m = TrafficModel(cfg)
    w = np.array([m.pareto_weight(i) for i in range(cfg.n_actors)])
    assert w.mean() == pytest.approx(1.0)
    assert np.all(w > 0)
    assert w.max() / np.median(w) > 3.0  # the "few hot lanes" shape
    # Hill-style sanity: the top decile carries an outsized share for
    # alpha=1.5 (would be ~10% under a uniform fleet)
    top = np.sort(w)[-cfg.n_actors // 10:]
    assert top.sum() / w.sum() > 0.2
    floor = TrafficModel(TrafficConfig(
        seed=3, n_actors=1, diurnal_amp=1.0, min_rows_per_sec=5.0,
        base_rows_per_sec=1.0))
    ts = np.arange(0.0, 120.0, 0.25)
    assert min(floor.rate(0, float(t)) for t in ts) >= 5.0


def test_renewal_flash_stream():
    """The unscripted flash stream is a seeded renewal process: every
    event lands inside the horizon with positive duration and an
    amplitude inside the configured band, and the stream replays."""
    cfg = TrafficConfig(seed=11, flash_rate_per_s=0.5, horizon_s=40.0,
                        flash_duration_s=(1.0, 2.0), flash_amp=(3.0, 5.0))
    ev = TrafficModel(cfg).flash_events()
    assert ev and ev == TrafficModel(cfg).flash_events()
    for start, dur, amp in ev:
        assert 0.0 < start < cfg.horizon_s
        assert 1.0 <= dur <= 2.0
        assert 3.0 <= amp <= 5.0


# --- admission policy -------------------------------------------------------

def test_admission_policy_classes():
    pol = AdmissionPolicy()
    assert [pol.classify_index(i) for i in range(4)] == [0, 1, 0, 1]
    # the fleet's trailing-int identity convention classifies by index
    assert pol.classify_actor("actor-3") == pol.classify_index(3)
    assert pol.classify_actor("proc-12") == pol.classify_index(12)
    # no trailing int: stable crc32 fallback (same across processes)
    assert (pol.classify_actor("learner")
            == pol.classify_actor("learner"))
    assert pol.class_name(0) == "rt" and pol.class_name(1) == "bulk"
    # bulk gets half the depth budget, floored at 1
    assert pol.depth_for(0, 96) == 96
    assert pol.depth_for(1, 96) == 48
    assert pol.depth_for(1, 1) == 1
    with pytest.raises(ValueError):
        AdmissionPolicy(classes=("a",), depth_fracs=(1.0, 0.5))
    with pytest.raises(ValueError):
        AdmissionPolicy(classes=("a", "b"), depth_fracs=(1.0, 0.0))


def test_shed_victim_no_priority_inversion():
    pol = AdmissionPolicy()
    # oldest item of the worst class present is the victim
    assert pol.shed_victim([0, 1, 0, 1], incoming_cls=0) == 1
    # incoming outranked by nothing queued: caller rejects the incoming
    # instead of evicting better-class work
    assert pol.shed_victim([0, 0, 0], incoming_cls=1) is None
    assert pol.shed_victim([], incoming_cls=0) is None
    # equal class is NOT an inversion — oldest equal-class item goes
    assert pol.shed_victim([1, 1], incoming_cls=1) == 0


# --- autoscaler + ledger ----------------------------------------------------

def _signals(queue=0.0, p95=0.0, depth=0.0, sheds=0.0):
    return {"serving_queue": queue, "serving_p95_ms": p95,
            "ingest_depth_frac": depth, "ingest_sheds": sheds}


def test_control_policy_hysteresis_and_cooldown():
    cfg = AutoscalerConfig(serving_rows_init=32, serving_rows_min=16,
                           serving_rows_max=128, cooldown_ticks=2)
    pol = ControlPolicy(cfg)
    state = pol.initial_state()
    hot = _signals(queue=cfg.queue_high + 1)
    dec, state = pol.decide(hot, state)
    assert dec["serving_rows"] == 64  # one doubling per move
    assert dec["serving_window_s"] == cfg.serving_window_hot_s
    # still hot, but inside the cooldown: no move
    dec, state = pol.decide(hot, state)
    assert "serving_rows" not in dec
    dec, state = pol.decide(hot, state)
    assert dec["serving_rows"] == 128
    # pinned at max from here
    dec, state = pol.decide(hot, state)
    dec, state = pol.decide(hot, state)
    assert "serving_rows" not in dec
    # the hysteresis gap: a calm-but-not-cold plane holds position
    mid = _signals(queue=(cfg.queue_low + cfg.queue_high) // 2)
    for _ in range(4):
        dec, state = pol.decide(mid, state)
        assert "serving_rows" not in dec
    cold = _signals()
    dec, state = pol.decide(cold, state)
    assert dec["serving_rows"] == 64
    assert dec["serving_window_s"] == cfg.serving_window_cold_s


def test_control_policy_ingest_and_dealer():
    """Ingest pressure deepens the shards AND paces the dealer down
    (the commit thread's lock windows go to draining); calm reverses
    both. A shed-counter delta alone counts as pressure."""
    cfg = AutoscalerConfig(ingest_capacity_init=64, dealer_deals_init=2,
                           dealer_deals_max=4, cooldown_ticks=0)
    pol = ControlPolicy(cfg)
    state = pol.initial_state()
    dec, state = pol.decide(_signals(sheds=5.0), state)  # delta 5 > 0
    assert dec["ingest_capacity"] == 128
    assert dec["dealer_deals"] == 1
    # same cumulative counter: delta 0 now, depth calm -> scale back
    dec, state = pol.decide(_signals(sheds=5.0), state)
    assert dec["ingest_capacity"] == 64
    assert dec["dealer_deals"] == 2


def test_extract_signals_total():
    """A missing provider, a provider_error section, or garbage values
    read as a calm plane — the controller degrades to do-nothing, its
    thread never dies on a half-built registry export."""
    assert extract_signals({}) == _signals()
    assert extract_signals({"serving": {"provider_error": "x"},
                            "ingest": None}) == _signals()
    sig = extract_signals({
        "serving": {"queue_depth": 3, "latency_ms": {"p95": "nan?"}},
        "ingest": {"sheds": 2, "admit_fails": 1,
                   "per_shard": [{"queue_depth": 5, "capacity": 10},
                                 {"queue_depth": 1, "capacity": 0}]},
    })
    assert sig["serving_queue"] == 3.0
    assert sig["serving_p95_ms"] == 0.0  # unparsable -> calm
    assert sig["ingest_depth_frac"] == 0.5  # max over shards, 0-cap skipped
    assert sig["ingest_sheds"] == 3.0


def test_ledger_replay_oracle_and_tamper():
    """Driving the autoscaler from a scripted sensor yields a ledger the
    pure controller reproduces bit for bit; the digest pins across two
    identical runs; a tampered decision breaks the oracle."""
    cfg = AutoscalerConfig(cooldown_ticks=1)
    script = ([_signals(queue=50.0, p95=80.0)] * 4
              + [_signals()] * 4
              + [_signals(depth=0.9, sheds=3.0)] * 4)

    def run_once():
        scaler = Autoscaler(
            cfg, actuators={},
            sensor=lambda: {},  # replaced per tick below
            ledger=ScalingLedger(), register_provider=False)
        for sig in script:
            scaler._sensor = lambda s=sig: {
                "serving": {"queue_depth": s["serving_queue"],
                            "latency_ms": {"p95": s["serving_p95_ms"]}},
                "ingest": {"sheds": s["ingest_sheds"], "admit_fails": 0,
                           "per_shard": [{"queue_depth": s["ingest_depth_frac"],
                                          "capacity": 1.0}]},
            }
            scaler.tick_once()
        return scaler

    a, b = run_once(), run_once()
    assert len(a.ledger) == len(script)
    assert replay_matches(cfg, a.ledger)
    assert a.ledger.digest() == b.ledger.digest()
    stats = a.autoscaler_stats()
    assert stats["decisions"] > 0 and stats["actuations"] == 0
    # tamper: flip one recorded decision -> the replay oracle fails
    recs = a.ledger.records()
    victim = next(r for r in recs if r["decisions"])
    tampered = ScalingLedger()
    for r in recs:
        if r is victim:
            r = dict(r, decisions={k: v + 1
                                   for k, v in r["decisions"].items()})
        tampered.append(r)
    assert not replay_matches(cfg, tampered)
    assert tampered.digest() != a.ledger.digest()
    # wall time rides the record but stays out of the canonical stream
    assert "t_wall" in recs[0] and "t_wall" not in canonical_record(recs[0])


def test_autoscaler_actuation_bounded_and_contained():
    """Wired actuators receive exactly the decided targets; an actuator
    that raises is degrade-and-count (journaled in the record's errors,
    loop alive); unknown knob names fail at construction."""
    cfg = AutoscalerConfig(cooldown_ticks=0)
    seen: list = []

    def boom(v):
        raise RuntimeError("actuator down")

    scaler = Autoscaler(
        cfg,
        actuators={"serving_rows": seen.append, "ingest_capacity": boom},
        sensor=lambda: {"serving": {"queue_depth": 99,
                                    "latency_ms": {"p95": 500.0}},
                        "ingest": {"sheds": 1, "per_shard": [
                            {"queue_depth": 9, "capacity": 10}]}},
        register_provider=False)
    rec = scaler.tick_once()
    assert seen == [rec["decisions"]["serving_rows"]]
    assert rec["errors"] and "ingest_capacity" in rec["errors"][0]
    assert scaler.stats["actuator_errors"] == 1
    # the errored knob's decision is still journaled + replay-covered
    assert replay_matches(cfg, scaler.ledger)
    with pytest.raises(ValueError):
        Autoscaler(cfg, actuators={"warp_factor": seen.append},
                   register_provider=False)


def test_live_capacity_setters():
    """The actuation surface the autoscaler drives: ingest-depth resize
    recomputes the shed watermark under the shard conds and dealer
    pacing clamps at >= 1 — both safe mid-flight."""
    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.replay.uniform import ReplayBuffer
    from d4pg_tpu.replay.sampler import SampleDealer
    from d4pg_tpu.replay.staging import DealtBlockRing

    svc = ReplayService(ReplayBuffer(512, 3, 2, seed=0), ingest_capacity=8,
                        shed_watermark=0.75, num_ingest_shards=2)
    try:
        svc.set_ingest_depth(64)
        stats = svc.ingest_stats()
        assert stats["ingest_capacity"] == 64
        for sh in stats["per_shard"]:
            assert sh["capacity"] == 64 and sh["shed_at"] == 48
        svc.set_ingest_depth(0)  # clamps, never a zero-capacity shard
        assert svc.ingest_stats()["ingest_capacity"] == 1
    finally:
        svc.close()
    dealer = SampleDealer(512, [DealtBlockRing(2)], n_shards=1, k=2,
                          batch_size=4, min_size=4, seed=0)
    dealer.set_pacing(3)
    assert dealer.max_deals_per_tick == 3
    dealer.set_pacing(-5)
    assert dealer.max_deals_per_tick == 1


# --- the committed artifact -------------------------------------------------

def test_elastic_artifact_schema():
    """The newest committed elastic artifact must carry the full A/B
    story with the gate PASSING: at equal seeded offered load the
    autoscaler arm has strictly fewer serving SLO breaches AND strictly
    fewer ingest shed rows, every shed is class-attributed, the scaling
    ledger replays bit-identically, and the run-gating oracles (lock
    hierarchy, crash containment, trace orphans) are all clean. A later
    PR that regresses any of it fails tier-1 here."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "elastic", "elastic_*.json")))
    assert arts, "no committed elastic artifact"
    with open(arts[-1]) as f:
        art = json.load(f)
    assert art["metric"] == "fleet_elastic" and art["schema"] == 1
    assert art["offered_deterministic"] is True
    assert len(art["offered_rows_per_s"]) >= 16
    drill = art["drill"]
    assert drill["metric"] == "elastic_chaos" and drill["schema"] == 1
    gate = drill["ab_gate"]
    assert gate["pass"] is True
    assert gate["slo_breaches_elastic"] < gate["slo_breaches_static"]
    assert gate["shed_rows_elastic"] < gate["shed_rows_static"]
    assert drill["hierarchy_violations"] == 0
    assert drill["contained_crashes"] == 0
    assert drill["trace"]["orphans"] == 0
    for arm_name in ("static", "elastic"):
        arm = drill["arms"][arm_name]
        assert arm["requests"]["sent"] > 0
        # every shed/reject is attributed to a class on both planes
        ing = arm["ingest"]
        if ing["shed_rows"] or ing["admit_fails"]:
            assert sum(ing["sheds_by_class"].values()) > 0
    elastic_arm = drill["arms"]["elastic"]["autoscaler"]
    assert elastic_arm["ledger_replay_ok"] is True
    assert elastic_arm["ticks"] > 0 and elastic_arm["actuations"] > 0
    assert elastic_arm["ledger_digest"]
