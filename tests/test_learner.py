"""Learner-layer tests: the jit'd D4PG update (SURVEY.md §4 test strategy).

Covers: state init/target equality, one-step mechanics (step counter, target
soft-update direction), loss decrease on a synthetic fixed-point task,
determinism (same seed => bitwise-identical params — the property that
replaces the reference's hogwild races by construction, SURVEY.md §5), PER
weight plumbing, and the MoG critic family end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.learner import D4PGConfig, act, act_deterministic, init_state, make_update
from d4pg_tpu.replay.uniform import TransitionBatch

OBS, ACT, B = 3, 1, 32


def _config(**kw):
    base = dict(obs_dim=OBS, act_dim=ACT, v_min=-10.0, v_max=10.0, n_atoms=11,
                hidden=(32, 32, 32))
    base.update(kw)
    return D4PGConfig(**base)


def _batch(rng, done_frac=0.25, gamma=0.99):
    done = (rng.random(B) < done_frac).astype(np.float32)
    return TransitionBatch(
        obs=rng.standard_normal((B, OBS)).astype(np.float32),
        action=rng.uniform(-1, 1, (B, ACT)).astype(np.float32),
        reward=rng.standard_normal(B).astype(np.float32),
        next_obs=rng.standard_normal((B, OBS)).astype(np.float32),
        done=done,
        discount=(gamma * (1.0 - done)).astype(np.float32),
    )


def test_init_targets_equal_online():
    config = _config()
    state = init_state(config, jax.random.key(0))
    chex = jax.tree_util.tree_all(
        jax.tree_util.tree_map(
            lambda a, b: jnp.array_equal(a, b),
            state.actor_params,
            state.target_actor_params,
        )
    )
    assert chex
    assert int(state.step) == 0


def test_update_step_mechanics(rng):
    config = _config()
    state = init_state(config, jax.random.key(0))
    update = make_update(config, donate=False)
    batch = _batch(rng)
    w = jnp.ones((B,), jnp.float32)
    new_state, metrics = update(state, batch, w)
    assert int(new_state.step) == 1
    assert metrics["td_error"].shape == (B,)
    assert np.isfinite(float(metrics["critic_loss"]))
    # targets moved strictly toward online params, by a tau-sized amount
    def moved(t_old, t_new, online):
        d_old = jnp.abs(t_old - online).sum()
        d_new = jnp.abs(t_new - online).sum()
        return float(d_new) <= float(d_old) + 1e-6

    flat_old = jax.tree_util.tree_leaves(state.target_critic_params)
    flat_new = jax.tree_util.tree_leaves(new_state.target_critic_params)
    flat_onl = jax.tree_util.tree_leaves(new_state.critic_params)
    assert all(moved(a, b, c) for a, b, c in zip(flat_old, flat_new, flat_onl))


def test_loss_decreases_on_fixed_task(rng):
    """On a fixed batch, repeated updates must reduce the critic loss."""
    config = _config(lr_actor=1e-3, lr_critic=1e-3)
    state = init_state(config, jax.random.key(1))
    update = make_update(config, donate=False, use_is_weights=False)
    batch = _batch(rng)
    first = None
    for i in range(60):
        state, metrics = update(state, batch)
        if first is None:
            first = float(metrics["critic_loss"])
    assert float(metrics["critic_loss"]) < first


def test_determinism_same_seed(rng):
    """Same seed + same data => bitwise-identical parameters (SURVEY.md §5:
    the synchronous design removes the reference's races by construction)."""
    config = _config()
    batch = _batch(rng)
    outs = []
    for _ in range(2):
        state = init_state(config, jax.random.key(7))
        update = make_update(config, donate=False, use_is_weights=False)
        for _ in range(3):
            state, _ = update(state, batch)
        outs.append(state)
    for a, b in zip(
        jax.tree_util.tree_leaves(outs[0].actor_params),
        jax.tree_util.tree_leaves(outs[1].actor_params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_is_weights_scale_loss(rng):
    """Zero IS weights must zero the critic gradient; uniform weights match
    the unweighted loss."""
    config = _config()
    state = init_state(config, jax.random.key(2))
    update = make_update(config, donate=False)
    batch = _batch(rng)
    _, m_uniform = update(state, batch, jnp.ones((B,), jnp.float32))
    s_zero, m_zero = update(state, batch, jnp.zeros((B,), jnp.float32))
    assert float(m_zero["critic_loss"]) == 0.0
    # with zero weights the critic params must not move
    for a, b in zip(
        jax.tree_util.tree_leaves(state.critic_params),
        jax.tree_util.tree_leaves(s_zero.critic_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)
    assert float(m_uniform["critic_loss"]) > 0.0


def test_mog_family_end_to_end(rng):
    """The reference's empty mixture_of_gaussian stub (models.py:63-65,
    85-87), implemented for real: full update runs and improves."""
    config = _config(critic_family="mog", n_components=3, mog_samples=16)
    state = init_state(config, jax.random.key(3))
    update = make_update(config, donate=False, use_is_weights=False)
    batch = _batch(rng)
    first = None
    for _ in range(40):
        state, metrics = update(state, batch)
        if first is None:
            first = float(metrics["critic_loss"])
    assert np.isfinite(float(metrics["critic_loss"]))
    assert metrics["td_error"].shape == (B,)


def test_multi_update_equals_sequential(rng):
    """make_multi_update (lax.scan K-per-dispatch) must match K sequential
    update_step calls bitwise — same PRNG chain, same Adam math."""
    from d4pg_tpu.learner import make_multi_update

    config = _config()
    K = 3
    batches = [_batch(np.random.default_rng(i)) for i in range(K)]
    w = np.ones((K, B), np.float32)

    seq_state = init_state(config, jax.random.key(11))
    seq_update = make_update(config, donate=False)
    for i in range(K):
        seq_state, seq_m = seq_update(seq_state, batches[i], jnp.asarray(w[i]))

    stacked = TransitionBatch(*[np.stack(x) for x in zip(*batches)])
    multi_state = init_state(config, jax.random.key(11))
    multi = make_multi_update(config, donate=False)
    multi_state, multi_m = multi(multi_state, stacked, jnp.asarray(w))

    assert int(multi_state.step) == K
    np.testing.assert_array_equal(
        np.asarray(multi_m["td_error"][-1]), np.asarray(seq_m["td_error"]))
    for a, b in zip(jax.tree_util.tree_leaves(seq_state.critic_params),
                    jax.tree_util.tree_leaves(multi_state.critic_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_update_loop_is_steady_state(rng):
    """The learner hot path must hit the jit cache after warmup: repeated
    update calls (fresh batch values, same shapes/dtypes) may not trigger a
    single XLA compilation. Guards the invariant bench.py's headline rate
    depends on — a weak-type or shape instability here would silently turn
    throughput numbers into compile-time measurements."""
    from d4pg_tpu.io.profiling import RecompileSentinel

    config = _config()
    state = init_state(config, jax.random.key(0))
    update = make_update(config, donate=False)
    state, _ = update(state, _batch(rng), jnp.ones((B,), jnp.float32))  # warmup
    with RecompileSentinel() as sentinel:
        for i in range(3):
            batch = _batch(np.random.default_rng(i))
            state, metrics = update(state, batch, jnp.ones((B,), jnp.float32))
    jax.block_until_ready(metrics["critic_loss"])
    sentinel.assert_clean("learner update loop")


def test_act_shapes_and_bounds(rng):
    config = _config()
    state = init_state(config, jax.random.key(4))
    obs = jnp.asarray(rng.standard_normal((B, OBS)), jnp.float32)
    a = act(config, state.actor_params, obs, jax.random.key(5), epsilon=0.3)
    assert a.shape == (B, ACT)
    assert float(jnp.max(jnp.abs(a))) <= 1.0
    g = act_deterministic(config, state.actor_params, obs)
    assert float(jnp.max(jnp.abs(g))) <= 1.0
    # exploratory differs from greedy
    assert float(jnp.max(jnp.abs(a - g))) > 0.0


def test_bfloat16_compute_dtype(rng):
    """bf16 matmuls (MXU-native): update runs, losses stay float32-finite,
    and the critic still improves on a fixed task."""
    config = _config(compute_dtype="bfloat16")
    state = init_state(config, jax.random.key(6))
    update = make_update(config, donate=False, use_is_weights=False)
    batch = _batch(rng)
    first = None
    for _ in range(40):
        state, metrics = update(state, batch)
        if first is None:
            first = float(metrics["critic_loss"])
    assert metrics["critic_loss"].dtype == jnp.float32
    assert float(metrics["critic_loss"]) < first
    # params stay float32 (bf16 is compute-only)
    leaf = jax.tree_util.tree_leaves(state.critic_params)[0]
    assert leaf.dtype == jnp.float32


def test_bad_compute_dtype_rejected():
    with pytest.raises(ValueError):
        _config(compute_dtype="float16")


def test_action_l2_penalty(rng):
    """action_l2 adds exactly l2 * mean(|pi(s)|^2) to the actor loss (the
    HER recipe's penalty; 0 = reference objective) and flows into training."""
    from d4pg_tpu.learner.update import _actor_loss_fn

    base_cfg = _config()
    pen_cfg = _config(action_l2=0.5)
    state = init_state(base_cfg, jax.random.key(0))
    batch = _batch(rng)
    actor = base_cfg.build_actor()
    a = actor.apply(state.actor_params, batch.obs)
    expected_pen = 0.5 * float(jnp.mean(jnp.square(a)))  # baselines norm
    base = float(_actor_loss_fn(base_cfg, state.actor_params,
                                state.critic_params, batch))
    pen = float(_actor_loss_fn(pen_cfg, state.actor_params,
                               state.critic_params, batch))
    np.testing.assert_allclose(pen - base, expected_pen, rtol=1e-5)
    # and the jit'd update accepts the config (static field, new cache key)
    update = make_update(pen_cfg, donate=False)
    new_state, metrics = update(state, batch, jnp.ones((B,), jnp.float32))
    assert np.isfinite(float(metrics["actor_loss"]))


def test_pallas_projection_selectable_and_equivalent(rng, monkeypatch):
    """--projection pallas routes the update through ops/projection.py
    (VERDICT r2 #5: the kernel must be reachable from the product) and
    produces the same training trajectory as the einsum formulation —
    the two implementations compute identical semantics, so after a few
    full updates the parameters must agree to float tolerance."""
    import d4pg_tpu.ops.projection as ops_projection

    calls = []
    real = ops_projection.projection_pallas
    monkeypatch.setattr(
        ops_projection, "projection_pallas",
        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1],
    )
    batch = _batch(rng)
    states = {}
    for projection in ("einsum", "pallas"):
        config = _config(projection=projection)
        state = init_state(config, jax.random.key(3))
        update = make_update(config, donate=False, use_is_weights=False)
        for _ in range(3):
            state, metrics = update(state, batch)
        states[projection] = state
        assert np.isfinite(float(metrics["critic_loss"]))
        # the kernel must actually be on the traced path — a dispatch
        # regression silently reverting both configs to the einsum would
        # otherwise pass the equivalence assert below trivially
        assert bool(calls) == (projection == "pallas")
    for a, b in zip(
        jax.tree_util.tree_leaves(states["einsum"].critic_params),
        jax.tree_util.tree_leaves(states["pallas"].critic_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_bad_projection_rejected():
    with pytest.raises(ValueError):
        _config(projection="scatter")
