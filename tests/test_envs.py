"""Environment-layer tests: rescaling parity, goal flattening, HER
relabeling (including the reference's stale-action bug fix), pool autoreset."""

import numpy as np
import pytest

from d4pg_tpu.envs import (
    EnvPool,
    FakeGoalEnv,
    PointMassEnv,
    flatten_goal_obs,
    get_preset,
    her_relabel,
    rescale_action,
)
from d4pg_tpu.envs.wrappers import inverse_rescale_action


def test_rescale_roundtrip(rng):
    """Affine parity with NormalizeAction (normalize_env.py:3-14)."""
    low, high = np.array([-2.0, 0.0]), np.array([2.0, 10.0])
    a = rng.uniform(-1, 1, (16, 2))
    scaled = rescale_action(a, low, high)
    assert (scaled >= low - 1e-6).all() and (scaled <= high + 1e-6).all()
    np.testing.assert_allclose(inverse_rescale_action(scaled, low, high), a, atol=1e-6)
    # endpoints map exactly
    np.testing.assert_allclose(rescale_action(np.array([-1.0, 1.0]), low, high),
                               np.array([low[0], high[1]]))


def test_flatten_goal_obs():
    obs = {"observation": np.ones(3), "desired_goal": np.zeros(2),
           "achieved_goal": np.ones(2)}
    flat = flatten_goal_obs(obs)
    assert flat.shape == (5,)
    plain = np.arange(4.0)
    np.testing.assert_array_equal(flatten_goal_obs(plain), plain)


def test_her_relabel_uses_per_step_actions(rng):
    """The bug fix for main.py:184: each relabeled transition must carry its
    OWN action, not the episode's last."""
    T, goal_dim = 20, 2
    env = FakeGoalEnv(seed=0)
    observation = rng.standard_normal((T, 2)).astype(np.float32)
    achieved = rng.standard_normal((T + 1, goal_dim)).astype(np.float32)
    # make actions identifiable: action[t] = [t, -t]
    action = np.stack([np.arange(T), -np.arange(T)], axis=-1).astype(np.float32)
    next_observation = rng.standard_normal((T, 2)).astype(np.float32)
    batch = her_relabel(observation, achieved, action, next_observation,
                        env.compute_reward, rng, her_ratio=1.0)
    assert batch.obs.shape[0] == T
    # recover t from the stored action's first coordinate; must be 0..T-1
    ts = batch.action[:, 0].astype(int)
    np.testing.assert_array_equal(np.sort(ts), np.arange(T))
    # obs = [observation[t], goal]: first 2 dims match the t-indexed rows
    np.testing.assert_allclose(batch.obs[:, :2], observation[ts], atol=0)


def test_her_relabel_future_goals_and_success(rng):
    """Goals must come from the episode's own future; achieved==goal at the
    sampled index implies reward 0 and done."""
    T = 10
    env = FakeGoalEnv(seed=0)
    achieved = np.linspace(0, 1, T + 1)[:, None].repeat(2, axis=1).astype(np.float32)
    observation = np.zeros((T, 2), np.float32)
    action = np.zeros((T, 2), np.float32)
    next_observation = np.zeros((T, 2), np.float32)
    batch = her_relabel(observation, achieved, action, next_observation,
                        env.compute_reward, rng, her_ratio=1.0)
    # rewards are in {-1, 0}; discount == 0 exactly where done
    assert set(np.unique(batch.reward)).issubset({-1.0, 0.0})
    np.testing.assert_array_equal(batch.discount == 0.0, batch.done == 1.0)
    assert (batch.done == 1.0).any()


def test_her_ratio_zero_empty(rng):
    env = FakeGoalEnv(seed=0)
    batch = her_relabel(np.zeros((5, 2), np.float32), np.zeros((6, 2), np.float32),
                        np.zeros((5, 2), np.float32), np.zeros((5, 2), np.float32),
                        env.compute_reward, rng, her_ratio=0.0)
    assert batch.obs.shape[0] == 0


def test_env_pool_autoreset_and_stats():
    horizon = 25
    pool = EnvPool([lambda s=i: PointMassEnv(horizon=horizon, seed=s)
                    for i in range(4)], seed=0)
    obs = pool.reset()
    assert obs.shape == (4, 4)
    steps = 0
    for _ in range(horizon):
        out = pool.step(np.zeros((4, 2), np.float32))
        steps += 1
    # all four envs truncated exactly at the horizon and auto-reset
    assert len(pool.episode_returns) == 4
    assert pool.episode_lengths == [horizon] * 4
    assert out.truncated.all()
    # final_obs differs from the post-reset obs on the done tick
    assert not np.allclose(out.obs, out.final_obs)
    pool.close()


def test_env_pool_reset_on_done_is_per_env():
    """Mixed horizons: only the done env is autoreset on its done tick —
    its ``obs`` row diverges from ``final_obs`` while live envs' rows
    stay identical (the serving lanes batch many envs through one
    request, so a pool-wide reset would corrupt the other lanes' rows)."""
    pool = EnvPool([lambda: PointMassEnv(horizon=5, seed=0),
                    lambda: PointMassEnv(horizon=9, seed=1)], seed=3)
    pool.reset()
    for _ in range(5):
        out = pool.step(np.full((2, 2), 0.3, np.float32))
    assert out.truncated.tolist() == [True, False]
    assert not np.allclose(out.obs[0], out.final_obs[0])
    np.testing.assert_array_equal(out.obs[1], out.final_obs[1])
    assert pool.episode_lengths == [5]
    pool.close()


def test_env_pool_seed_determinism():
    """Two pools built from the same (ctor seeds, pool seed) reproduce
    the same trajectory under the same actions, and a second reset()
    replays the same initial obs (reset re-seeds env i with seed+i)."""
    def build():
        return EnvPool([lambda s=i: PointMassEnv(horizon=30, seed=s)
                        for i in range(3)], seed=7)

    a, b = build(), build()
    rng = np.random.default_rng(4)
    actions = rng.uniform(-1, 1, (12, 3, 2)).astype(np.float32)
    first = a.reset()
    np.testing.assert_array_equal(first, b.reset())
    for t in range(12):
        oa, ob = a.step(actions[t]), b.step(actions[t])
        np.testing.assert_array_equal(oa.obs, ob.obs)
        np.testing.assert_array_equal(oa.reward, ob.reward)
        np.testing.assert_array_equal(oa.final_obs, ob.final_obs)
    np.testing.assert_array_equal(a.reset(), first)
    a.close(), b.close()


def test_env_pool_single_env_matches_scalar():
    """1-env pool == the raw env stepped by hand (the serving refactor's
    E=1 anchor): same seed path, identical obs/reward/done stream, with
    the pool's tanh->space action rescale applied explicitly."""
    from d4pg_tpu.envs import rescale_action

    pool = EnvPool([lambda: PointMassEnv(horizon=8, seed=2)], seed=13)
    env = PointMassEnv(horizon=8, seed=2)
    obs_p = pool.reset()
    obs_s, _ = env.reset(seed=13)  # pool seeds env 0 with seed + 0
    np.testing.assert_array_equal(obs_p[0], np.float32(obs_s))
    rng = np.random.default_rng(1)
    low = np.asarray(env.action_space.low, np.float32)
    high = np.asarray(env.action_space.high, np.float32)
    for t in range(10):  # crosses the horizon-8 autoreset boundary
        a = rng.uniform(-1, 1, (1, 2)).astype(np.float32)
        out = pool.step(a)
        obs_s, r, term, trunc, _ = env.step(
            rescale_action(a, low, high)[0])
        np.testing.assert_array_equal(out.final_obs[0], np.float32(obs_s))
        assert out.reward[0] == np.float32(r)
        assert bool(out.terminated[0]) == term
        assert bool(out.truncated[0]) == trunc
        if term or trunc:
            obs_s, _ = env.reset()
        np.testing.assert_array_equal(out.obs[0], np.float32(obs_s))
    pool.close()


def test_fake_goal_env_contract():
    env = FakeGoalEnv(seed=3)
    obs, _ = env.reset(seed=3)
    assert set(obs) == {"observation", "achieved_goal", "desired_goal"}
    o2, r, term, trunc, info = env.step(np.array([0.5, 0.5]))
    assert r in (-1.0, 0.0) and "is_success" in info
    # vectorized compute_reward
    r_vec = env.compute_reward(np.zeros((7, 2)), np.zeros((7, 2)))
    np.testing.assert_array_equal(r_vec, np.zeros(7))


def test_presets():
    p = get_preset("Pendulum-v1")
    assert p.v_max == 0.0 and p.v_min < 0
    q = get_preset("SomeUnknownEnv-v9")
    assert q.v_min < 0 < q.v_max
