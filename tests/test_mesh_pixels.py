"""Composition smoke: the conv-encoder pixel recipe executes under the
{data, model} mesh (VERDICT r5 "What's missing" #1 — the pixel stack and
the sharded fused replay plane had never run TOGETHER; the round-5
share_encoder x K-scan double-donation bug was exactly this class of
composition fault, caught only on the single-device path).

Tiny shapes on the 8-virtual-CPU-device mesh: --share_encoder
--frame_stack 3 --augment shift resolved through ExperimentConfig (the
real flag path, including '--projection auto' resolving statically to
einsum for mesh learners), uint8 pixel rows in the sharded device ring,
one fused chunk through make_sharded_fused_chunk.

Plus the real-shape EQUIVALENCE gate (ISSUE 14): the same 84x84xstack
[K, B] pixel chunk through the rule-sharded {data, model} scanned
update vs the single-device one, params and metrics within the declared
tolerance below. The fused chunk's sampling prologue is shard-local by
construction (each device draws from ITS ring shard with a fold_in'd
key), so sampled streams cannot coincide across layouts — the
equivalence claim lives exactly in the update math the two paths share,
on identical staged batches."""

import jax
import numpy as np
import pytest

from d4pg_tpu.config import ExperimentConfig
from d4pg_tpu.learner import init_state
from d4pg_tpu.learner.fused import make_sharded_fused_chunk
from d4pg_tpu.parallel import MeshSpec, make_mesh
from d4pg_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from d4pg_tpu.replay.sharded_per import ShardedFusedReplay
from d4pg_tpu.replay.uniform import TransitionBatch

SHAPE = (8, 8, 9)  # 8px frames, frame_stack=3 -> 3*3 stacked channels
REAL_SHAPE = (84, 84, 9)  # the DrQ/D4PG-pixels convention at frame_stack=3
ACT = 2

# Declared tolerance for mesh-vs-single-device equivalence: under GSPMD
# the loss mean over the global batch becomes an XLA all-reduce whose
# float32 summation ORDER differs from the single-device reduction;
# Adam's per-parameter normalization (g / (sqrt(v) + eps)) then scales
# that reorder noise up where second moments are near zero. Everything
# else is identical math on identical inputs (same staged batches, same
# PRNG chain — the augment shifts draw per-sample fold_in keys, which
# GSPMD partitions value-preservingly; see ops/augment.py). Measured on
# the 8-virtual-device CPU mesh: max abs 2.9e-7, max rel 3.0e-4 over
# all param subtrees after K=2 steps — the bounds below keep ~2x slack.
EQUIV_RTOL = 5e-4
EQUIV_ATOL = 1e-6


def _pixel_batch(rng, n, shape=SHAPE):
    return TransitionBatch(
        obs=rng.integers(0, 255, (n, *shape)).astype(np.uint8),
        action=rng.uniform(-1, 1, (n, ACT)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.integers(0, 255, (n, *shape)).astype(np.uint8),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )


def _pixel_config(dp, shape=SHAPE, augment_pad=1, batch_size=16):
    cfg = ExperimentConfig(
        env="pixel-point", share_encoder=True, frame_stack=3,
        augment="shift", augment_pad=augment_pad, encoder_width=8,
        batch_size=batch_size, n_atoms=11, v_min=-10.0, v_max=10.0,
        hidden=(16, 16), data_parallel=dp)
    return cfg.learner_config(shape, ACT)


def test_pixel_share_encoder_fused_chunk_on_data_model_mesh(rng):
    mesh = make_mesh(MeshSpec(data_parallel=4, model_parallel=2))
    assert mesh.shape[DATA_AXIS] == 4 and mesh.shape[MODEL_AXIS] == 2
    config = _pixel_config(dp=4)
    assert config.pixels and config.share_encoder
    assert config.augment == "shift"
    # '--projection auto' must resolve STATICALLY to einsum under a mesh
    # (the Pallas kernels have no GSPMD partitioning rule)
    assert config.projection == "einsum"

    buf = ShardedFusedReplay(64, SHAPE, ACT, mesh, alpha=0.6,
                             obs_dtype=np.uint8)
    buf.add(_pixel_batch(rng, 64))
    buf.drain()
    assert np.asarray(buf.storage.obs).dtype == np.uint8  # packed pixels

    state = init_state(config, jax.random.key(0))
    fn = make_sharded_fused_chunk(config, mesh, k=2, batch_size=16,
                                  alpha=0.6, donate=False)
    s1, t1, m = fn(state, buf.trees, buf.storage, buf.size)
    assert int(jax.device_get(s1.step)) == 2
    assert m["td_error"].shape == (2, 16)
    for name in ("critic_loss", "actor_loss", "q_mean"):
        assert np.isfinite(np.asarray(m[name])).all(), name
    # the share_encoder tie must hold through the sharded chunk: the
    # actor's conv encoder IS the critic's after every update
    actor_enc = jax.device_get(s1.actor_params["params"]["encoder"])
    critic_enc = jax.device_get(s1.critic_params["params"]["encoder"])
    jax.tree_util.tree_map(np.testing.assert_array_equal,
                           actor_enc, critic_enc)


def test_pixel_mesh_chunk_matches_single_device_shapes(rng):
    """The data-parallel pixel chunk and the single-device fused chunk
    agree on metric/state structure (composition produces the same
    training artifacts the single-device path does)."""
    from d4pg_tpu.learner.fused import make_fused_chunk
    from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

    mesh = make_mesh(MeshSpec(data_parallel=2, model_parallel=1),
                     devices=jax.devices()[:2])
    config = _pixel_config(dp=2)
    buf_m = ShardedFusedReplay(32, SHAPE, ACT, mesh, alpha=0.6,
                               obs_dtype=np.uint8)
    buf_s = FusedDeviceReplay(32, SHAPE, ACT, alpha=0.6,
                              obs_dtype=np.uint8, block_rows=16)
    batch = _pixel_batch(rng, 32)
    for b in (buf_m, buf_s):
        b.add(batch)
        b.drain()
    fn_m = make_sharded_fused_chunk(config, mesh, k=2, batch_size=16,
                                    alpha=0.6, donate=False)
    fn_s = make_fused_chunk(config, k=2, batch_size=16, alpha=0.6,
                            donate=False)
    state = init_state(config, jax.random.key(0))
    _, _, m_m = fn_m(state, buf_m.trees, buf_m.storage, buf_m.size)
    _, _, m_s = fn_s(state, buf_s.trees, buf_s.storage, buf_s.size)
    assert m_m["td_error"].shape == m_s["td_error"].shape
    assert np.isfinite(np.asarray(m_m["critic_loss"])).all()
    assert np.isfinite(np.asarray(m_s["critic_loss"])).all()


def test_real_shape_pixel_mesh_update_matches_single_device(rng):
    """The equivalence gate at REAL shape (84x84, frame_stack=3): the
    SAME staged [K, B] pixel chunk through the rule-sharded {data, model}
    scanned update vs the single-device one, from the same initial state
    — every param subtree and every metric within the declared tolerance
    (EQUIV_RTOL/EQUIV_ATOL above; see the module docstring for why the
    comparison pins the update, not the fused chunk's shard-local
    sampling). This is what the 8x8 smoke above cannot certify: the conv
    encoder's model-axis tenancy, the DrQ shift at real pad radius and
    the all-reduced loss only take their production shapes here."""
    from d4pg_tpu.learner.replica import PARAM_FIELDS
    from d4pg_tpu.learner.update import make_multi_update
    from d4pg_tpu.parallel import make_sharded_multi_update
    from d4pg_tpu.parallel.data_parallel import (
        replicate_state,
        shard_stacked,
    )

    k, batch = 2, 8
    config = _pixel_config(dp=2, shape=REAL_SHAPE, augment_pad=4,
                           batch_size=batch)
    assert config.pixels and config.share_encoder
    assert config.projection == "einsum"

    flat = _pixel_batch(rng, k * batch, shape=REAL_SHAPE)
    batches = TransitionBatch(
        *[np.reshape(arr, (k, batch) + arr.shape[1:]) for arr in flat])
    w = np.ones((k, batch), np.float32)
    state0 = init_state(config, jax.random.key(0))

    fn_single = make_multi_update(config, donate=False)
    s_single, m_single = fn_single(state0, batches, w)

    mesh = make_mesh(MeshSpec(data_parallel=2, model_parallel=2),
                     devices=jax.devices()[:4])
    fn_mesh = make_sharded_multi_update(config, mesh, donate=False)
    s_mesh, m_mesh = fn_mesh(replicate_state(state0, mesh),
                             shard_stacked(batches, mesh),
                             shard_stacked(w, mesh))

    assert int(jax.device_get(s_mesh.step)) == \
        int(jax.device_get(s_single.step)) == k
    for f in PARAM_FIELDS:
        a = jax.device_get(getattr(s_single, f))
        b = jax.device_get(getattr(s_mesh, f))
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(
                x, y, rtol=EQUIV_RTOL, atol=EQUIV_ATOL), a, b)
    for name in ("critic_loss", "actor_loss", "q_mean", "td_error"):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(m_single[name])),
            np.asarray(jax.device_get(m_mesh[name])),
            rtol=EQUIV_RTOL, atol=EQUIV_ATOL, err_msg=name)
    # the share_encoder tie survives the sharded update at real shape
    jax.tree_util.tree_map(
        np.testing.assert_array_equal,
        jax.device_get(s_mesh.actor_params["params"]["encoder"]),
        jax.device_get(s_mesh.critic_params["params"]["encoder"]))
