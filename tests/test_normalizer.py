"""Running observation normalizer (envs/normalizer.py) + actor wiring."""

import numpy as np

from d4pg_tpu.distributed import ReplayService, WeightStore
from d4pg_tpu.distributed.actor import ActorConfig, GoalActorWorker
from d4pg_tpu.envs import FakeGoalEnv
from d4pg_tpu.envs.normalizer import RunningMeanStd
from d4pg_tpu.learner import D4PGConfig
from d4pg_tpu.replay import ReplayBuffer


def test_running_mean_std_matches_numpy_oracle(rng):
    norm = RunningMeanStd(5, eps=1e-8)
    chunks = [rng.normal(3.0, 2.0, (n, 5)) * (1 + np.arange(5))
              for n in (1, 7, 64, 128)]
    for c in chunks:
        norm.update(c)
    all_rows = np.concatenate(chunks)
    mean, std = norm.stats()
    np.testing.assert_allclose(mean, all_rows.mean(0), rtol=1e-10)
    np.testing.assert_allclose(std, all_rows.std(0), rtol=1e-6)
    z = norm.normalize(all_rows)
    np.testing.assert_allclose(z.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(z.std(0), 1.0, atol=1e-3)


def test_normalize_clips_and_floors_std():
    norm = RunningMeanStd(2, clip=5.0, eps=1e-2)
    norm.update(np.ones((100, 2)))  # zero-variance dims
    z = norm.normalize(np.array([[1.0, 1e9]]))
    assert z[0, 0] == 0.0
    assert z[0, 1] == 5.0  # clipped, not inf (std floored at eps)


def test_state_dict_roundtrip(rng):
    a = RunningMeanStd(3)
    a.update(rng.normal(0, 1, (50, 3)))
    b = RunningMeanStd(3)
    b.load_state_dict(a.state_dict())
    x = rng.normal(0, 1, (10, 3))
    np.testing.assert_array_equal(a.normalize(x), b.normalize(x))
    # continued updates agree too (count/m2 restored, not just mean/std)
    more = rng.normal(2, 3, (30, 3))
    a.update(more)
    b.update(more)
    np.testing.assert_allclose(a.stats()[1], b.stats()[1], rtol=1e-12)


def test_service_drain_normalizes_rows():
    """The ReplayService is the single writer: actors stream RAW rows, the
    drain thread folds them into the statistics and inserts normalized."""
    obs_dim = 2 + 2
    config = D4PGConfig(obs_dim=obs_dim, act_dim=2, v_min=-50, v_max=0,
                        n_atoms=11, hidden=(16, 16))
    buf = ReplayBuffer(10_000, obs_dim, 2)
    norm = RunningMeanStd(obs_dim)
    svc = ReplayService(buf, obs_norm=norm)
    ws = WeightStore()
    actor = GoalActorWorker("g0", config, ActorConfig(gamma=0.98),
                            FakeGoalEnv(horizon=30, seed=0), svc, ws,
                            her_ratio=1.0, rng_seed=2, obs_norm=norm)
    for _ in range(4):
        actor.run_episode(max_steps=30)
    svc.flush()
    n = len(svc)
    assert n > 0
    rows = buf.sample(min(n, 64))
    # stored rows are standardized: bounded by the clip and roughly centered
    assert np.abs(rows.obs).max() <= norm.clip + 1e-6
    assert np.abs(rows.obs.mean()) < 1.5
    # the estimator accumulated original AND relabeled rows
    assert norm.state_dict()["count"] > 0
    svc.close()


def test_norm_stats_ride_the_weight_channel():
    """Remote actors get (mean, std) with the weights: WeightServer embeds
    the store's published stats, WeightClient exposes them, and the actor
    builds a FrozenNormalizer from the pull."""
    import jax as _jax

    from d4pg_tpu.distributed.weight_server import WeightClient, WeightServer
    from d4pg_tpu.envs.normalizer import FrozenNormalizer
    from d4pg_tpu.learner import init_state

    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    store = WeightStore()
    norm = RunningMeanStd(4)
    norm.update(np.arange(40, dtype=np.float64).reshape(10, 4))
    store.publish(init_state(config, _jax.random.key(0)).actor_params,
                  step=7, norm_stats=norm.stats())
    server = WeightServer(store)
    client = WeightClient("127.0.0.1", server.port)
    try:
        got = client.get_if_newer(0)
        assert got is not None
        assert client.norm_stats is not None
        mean, std = norm.stats()
        np.testing.assert_allclose(client.norm_stats[0], mean)
        np.testing.assert_allclose(client.norm_stats[1], std)
        # the actor-side view normalizes identically to the live estimator
        frozen = FrozenNormalizer(*client.norm_stats)
        x = np.random.default_rng(0).normal(0, 10, (6, 4))
        np.testing.assert_allclose(frozen.normalize(x), norm.normalize(x),
                                   rtol=1e-6)
    finally:
        client.close()
        server.close()


def test_synced_rms_single_process_matches_direct():
    """SyncedRunningMeanStd.sync() (1-process allgather) must fold the
    delta into the global stats exactly like a direct RunningMeanStd
    update, and leave the delta empty."""
    import numpy as np

    from d4pg_tpu.envs.normalizer import RunningMeanStd, SyncedRunningMeanStd

    rng = np.random.default_rng(0)
    a, b = rng.standard_normal((64, 5)) * 3 + 1, rng.standard_normal((32, 5))
    direct = RunningMeanStd(5)
    synced = SyncedRunningMeanStd(5)
    for chunk in (a, b):
        direct.update(chunk)
        synced.update(chunk)
    assert synced.stats()[0].max() == 0  # global untouched before sync
    synced.sync()
    np.testing.assert_allclose(synced.stats()[0], direct.stats()[0], rtol=1e-12)
    np.testing.assert_allclose(synced.stats()[1], direct.stats()[1], rtol=1e-12)
    assert synced._delta._count == 0
    synced.sync()  # empty delta: a second sync must be a no-op
    np.testing.assert_allclose(synced.stats()[0], direct.stats()[0], rtol=1e-12)


def test_rms_merge_matches_update():
    import numpy as np

    from d4pg_tpu.envs.normalizer import RunningMeanStd

    rng = np.random.default_rng(1)
    x, y = rng.standard_normal((40, 3)), rng.standard_normal((24, 3)) + 2
    one = RunningMeanStd(3)
    one.update(np.concatenate([x, y]))
    left, right = RunningMeanStd(3), RunningMeanStd(3)
    left.update(x)
    right.update(y)
    left.merge(right._count, right._mean, right._m2)
    np.testing.assert_allclose(left.stats()[0], one.stats()[0], rtol=1e-12)
    np.testing.assert_allclose(left.stats()[1], one.stats()[1], rtol=1e-12)
