"""Cross-host plane tests: weight server/client roundtrip, the remote actor
runner against an in-process learner service, and the async actor mode."""

import threading
import time

import jax
import numpy as np
import pytest

from d4pg_tpu.config import ExperimentConfig
from d4pg_tpu.distributed import ReplayService, TransitionReceiver, WeightStore
from d4pg_tpu.distributed.weight_server import (
    WeightClient,
    WeightServer,
    _flatten,
    _unflatten,
)
from d4pg_tpu.learner import D4PGConfig, init_state
from d4pg_tpu.replay import ReplayBuffer


def test_flatten_roundtrip():
    tree = {"params": {"fc1": {"kernel": np.ones((2, 3)), "bias": np.zeros(3)},
                       "out": {"kernel": np.full((3, 1), 2.0)}}}
    flat = _flatten(tree)
    assert set(flat) == {"params/fc1/kernel", "params/fc1/bias",
                         "params/out/kernel"}
    back = _unflatten(flat)
    np.testing.assert_array_equal(back["params"]["fc1"]["kernel"],
                                  tree["params"]["fc1"]["kernel"])


def test_weight_server_client_roundtrip():
    config = D4PGConfig(obs_dim=3, act_dim=1, n_atoms=11, hidden=(8, 8))
    state = init_state(config, jax.random.key(0))
    store = WeightStore()
    server = WeightServer(store, host="127.0.0.1")
    client = WeightClient("127.0.0.1", server.port)
    assert client.get_if_newer(0) is None  # nothing published yet
    store.publish(state.actor_params, step=42)
    got = client.get_if_newer(0)
    assert got is not None
    version, params = got
    assert version == 1 and client.step == 42
    for a, b in zip(jax.tree_util.tree_leaves(state.actor_params),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert client.get_if_newer(version) is None  # up to date
    client.close()
    server.close()


def test_remote_actor_streams_to_learner():
    """Full remote plane: actor_main.run_actor on 'another host' (localhost)
    feeds the learner's receiver and pulls weights from its server."""
    from d4pg_tpu.actor_main import run_actor

    cfg = ExperimentConfig(env="point", num_envs=2, max_steps=20, n_steps=2,
                           v_min=-5.0, v_max=0.0, hidden=(16, 16), n_atoms=11)
    obs_dim, act_dim = 4, 2
    config = cfg.learner_config(obs_dim, act_dim)
    service = ReplayService(ReplayBuffer(10_000, obs_dim, act_dim))
    store = WeightStore()
    store.publish(init_state(config, jax.random.key(0)).actor_params, step=0)
    receiver = TransitionReceiver(lambda b, aid, count: service.add(
        b, actor_id=aid, count_env_steps=count),
                                  host="127.0.0.1")
    server = WeightServer(store, host="127.0.0.1")

    steps = run_actor(cfg, "127.0.0.1", receiver.port, server.port,
                      actor_id="remote-test", max_ticks=30)
    deadline = time.monotonic() + 5.0
    while len(service) < 41 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert steps == 60  # 30 ticks x 2 envs
    assert len(service) >= 41  # n-step folding holds a few back
    receiver.close()
    server.close()
    service.close()


def test_async_actor_training(tmp_path):
    """Decoupled mode: actors stream in background threads while the
    learner trains continuously."""
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=5,
        eval_trials=1, batch_size=16, memory_size=5000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, async_actors=True,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
    assert "grad_steps_per_sec" in metrics
    # async actors kept collecting beyond the warmup
    assert metrics["env_steps"] > 100


def test_remote_goal_actor_her_over_the_wire():
    """Remote HER: a goal actor on 'another host' streams originals AND
    relabels; the count_env_steps frame flag keeps the learner's env-step
    counter at the original rows only (no (1+her_ratio)x inflation)."""
    from d4pg_tpu.actor_main import run_actor

    cfg = ExperimentConfig(env="fake-goal", her=True, her_ratio=1.0,
                           max_steps=20, n_steps=1, v_min=-50.0, v_max=0.0,
                           hidden=(16, 16), n_atoms=11)
    obs_dim, act_dim = 4, 2  # 2 obs + 2 goal
    config = cfg.learner_config(obs_dim, act_dim)
    service = ReplayService(ReplayBuffer(10_000, obs_dim, act_dim))
    store = WeightStore()
    store.publish(init_state(config, jax.random.key(0)).actor_params, step=0)
    receiver = TransitionReceiver(lambda b, aid, count: service.add(
        b, actor_id=aid, count_env_steps=count), host="127.0.0.1")
    server = WeightServer(store, host="127.0.0.1")

    steps = run_actor(cfg, "127.0.0.1", receiver.port, server.port,
                      actor_id="remote-her", max_ticks=25)
    deadline = time.monotonic() + 5.0
    while len(service) < 2 * steps and time.monotonic() < deadline:
        time.sleep(0.02)
    assert steps > 0
    # originals + her_ratio=1.0 relabels arrived...
    assert len(service) == 2 * steps
    # ...but only originals count as env interaction
    assert service.env_steps == steps
    receiver.close()
    server.close()
    service.close()


def _mini_batch(obs_dim=4, act_dim=2, n=8):
    rng = np.random.default_rng(0)
    from d4pg_tpu.replay.uniform import TransitionBatch

    done = np.zeros(n, np.float32)
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=done,
        discount=(0.99 * (1 - done)).astype(np.float32),
    )


def test_fleet_survives_learner_restart():
    """VERDICT r3 #5, fleet side: kill the learner's servers mid-run — the
    sender reconnects with backoff and delivers the in-flight frame to the
    RESTARTED receiver on the same ports; the weight client degrades to
    stale weights (returns None) while the server is down and resumes
    pulling after the restart. The reference has no story here at all: a
    dead parent process ends the whole run (main.py:399-405)."""
    from d4pg_tpu.distributed.transport import TransitionSender

    obs_dim, act_dim = 4, 2
    config = D4PGConfig(obs_dim=obs_dim, act_dim=act_dim, n_atoms=11,
                        hidden=(8, 8))
    got: list = []
    receiver = TransitionReceiver(lambda b, aid, count: got.append(b),
                                  host="127.0.0.1")
    store = WeightStore()
    store.publish(init_state(config, jax.random.key(0)).actor_params, step=1)
    server = WeightServer(store, host="127.0.0.1")
    t_port, w_port = receiver.port, server.port

    sender = TransitionSender("127.0.0.1", t_port, actor_id="fleet-0",
                              retry_timeout=30.0)
    client = WeightClient("127.0.0.1", w_port, down_timeout=30.0,
                          reconnect_interval=1.0)
    sender.send(_mini_batch(obs_dim, act_dim))
    assert client.get_if_newer(0) is not None
    deadline = time.monotonic() + 5.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(got) == 1

    # learner "dies": both planes vanish
    receiver.close()
    server.close()
    # stale-weight degradation: pulls fail soft, never raise
    assert client.get_if_newer(0) is None
    assert client.get_if_newer(0) is None

    # sends while the learner is DOWN: TCP lets the FIRST post-death write
    # land in the kernel buffer ("success", frame lost — benign for replay
    # ingest); the SECOND observes the reset and must block in the
    # reconnect-retry loop instead of raising
    sent = threading.Event()

    def late_send():
        sender.send(_mini_batch(obs_dim, act_dim))  # may be silently lost
        sender.send(_mini_batch(obs_dim, act_dim))  # must retry + deliver
        sent.set()

    t = threading.Thread(target=late_send, daemon=True)
    t.start()
    time.sleep(0.8)
    assert not sent.is_set()  # still down, still retrying

    # ...until the learner RESTARTS on the same ports
    receiver2 = TransitionReceiver(lambda b, aid, count: got.append(b),
                                   host="127.0.0.1", port=t_port)
    store2 = WeightStore()
    store2.publish(init_state(config, jax.random.key(1)).actor_params,
                   step=2)
    server2 = WeightServer(store2, host="127.0.0.1", port=w_port)

    assert sent.wait(timeout=20.0), "sender did not re-attach after restart"
    deadline = time.monotonic() + 10.0
    while len(got) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(got) == 2
    # weight pulls resume against the restarted server
    deadline = time.monotonic() + 10.0
    fresh = None
    while fresh is None and time.monotonic() < deadline:
        fresh = client.get_if_newer(0)
        if fresh is None:
            time.sleep(0.2)
    assert fresh is not None and client.step == 2

    sender.close()
    client.close()
    receiver2.close()
    server2.close()
