"""Serving-plane tests (``d4pg_tpu/serving``).

The acceptance set for the continuous-batching inference service: wire
protocol framing + CRC torn-rejection, the 1-env lane-vs-legacy-actor
bitwise parity oracle (the refactor's safety net), batching/padding
correctness against a direct ``act_deterministic`` call, fenced
(generation, version) adoption, the client degradation ladder
(timeout -> cached params -> uniform warmup, every rung counted), a
small server-kill chaos smoke with all three run-gating oracles, and
the bench-artifact serving schema gate.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import threading
import time

import jax
import numpy as np
import pytest

from d4pg_tpu.distributed.transport import _recv_exact
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.envs import EnvPool, PointMassEnv
from d4pg_tpu.learner.state import D4PGConfig, init_state
from d4pg_tpu.learner.update import act_deterministic
from d4pg_tpu.serving import (
    ActorConfig,
    LocalPolicyClient,
    PolicyInferenceServer,
    RemotePolicyClient,
    ServingChaos,
    VectorActorLane,
)
from d4pg_tpu.serving import protocol

pytestmark = pytest.mark.serving

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = D4PGConfig(obs_dim=4, act_dim=2, v_min=-50.0, v_max=0.0,
                 n_atoms=11, hidden=(32, 32))


def _published_store(seed: int = 0) -> WeightStore:
    store = WeightStore()
    params = init_state(CFG, jax.random.key(seed)).actor_params
    store.publish(params, step=1, to_host=False)
    return store


# ------------------------------------------------------ wire protocol --


def test_protocol_request_roundtrip():
    obs = np.arange(12, dtype=np.float32).reshape(3, 4)
    frame = protocol.encode_request(42, obs, trace=(7, 1.5))
    magic, body_len = protocol.HEADER.unpack(frame[:protocol.HEADER.size])
    assert magic == protocol.MAGIC_REQUEST
    body = frame[protocol.HEADER.size:]
    assert len(body) == body_len
    req = protocol.decode_request(body)
    assert req["req_id"] == 42
    assert req["trace"] == (7, 1.5)
    np.testing.assert_array_equal(req["obs"], obs)


def test_protocol_response_roundtrip_and_statuses():
    acts = np.linspace(-1, 1, 8, dtype=np.float32).reshape(4, 2)
    body = protocol.encode_response(9, protocol.STATUS_OK, 2, 17,
                                    acts)[protocol.HEADER.size:]
    rsp = protocol.decode_response(body)
    assert (rsp["status"], rsp["generation"], rsp["version"]) == (0, 2, 17)
    np.testing.assert_array_equal(rsp["actions"], acts)
    # error statuses carry no payload but echo the req_id
    body = protocol.encode_response(9, protocol.STATUS_NO_PARAMS, 0, 0,
                                    None)[protocol.HEADER.size:]
    rsp = protocol.decode_response(body)
    assert rsp["status"] == protocol.STATUS_NO_PARAMS
    assert rsp["actions"] is None and rsp["req_id"] == 9


def test_protocol_torn_frames_rejected():
    obs = np.ones((2, 4), np.float32)
    body = bytearray(protocol.encode_request(5, obs)[protocol.HEADER.size:])
    body[-1] ^= 0xFF
    with pytest.raises(protocol.TornFrameError) as ei:
        protocol.decode_request(bytes(body))
    assert ei.value.meta["req_id"] == 5  # server echoes it as BAD_REQUEST
    acts = np.ones((2, 2), np.float32)
    body = bytearray(protocol.encode_response(
        6, protocol.STATUS_OK, 0, 1, acts)[protocol.HEADER.size:])
    body[-2] ^= 0x01
    with pytest.raises(protocol.TornFrameError):
        protocol.decode_response(bytes(body))


def test_protocol_bad_magic_and_truncation():
    a, b = socket.socketpair()
    try:
        a.sendall(protocol.HEADER.pack(0xBEEF, 4) + b"xxxx")
        with pytest.raises(protocol.ProtocolError, match="magic"):
            protocol.read_frame(b, protocol.MAGIC_REQUEST, _recv_exact)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        # frame claims 64 body bytes but the peer dies after 5
        a.sendall(protocol.HEADER.pack(protocol.MAGIC_REQUEST, 64) + b"short")
        a.close()
        with pytest.raises(protocol.ProtocolError, match="mid-frame"):
            protocol.read_frame(b, protocol.MAGIC_REQUEST, _recv_exact)
    finally:
        b.close()
    with pytest.raises(protocol.ProtocolError, match="too short"):
        protocol.decode_request(b"\x00")


# ------------------------------------------------- the parity oracle ---


class _Capture:
    """ReplayService-shaped sink recording every folded batch."""

    def __init__(self):
        self.batches = []

    def add(self, batch, actor_id=None, block=True, timeout=None,
            count_env_steps=True):
        self.batches.append(batch)
        return True


def _stream(sink: _Capture) -> dict:
    return {f: np.concatenate([getattr(b, f) for b in sink.batches])
            for f in sink.batches[0]._fields}


def test_lane_reproduces_legacy_actor_bitwise():
    """THE refactor oracle: a 1-env VectorActorLane with an in-process
    LocalPolicyClient must emit the legacy ``ActorWorker``'s transition
    stream seed-for-seed, bitwise — acting, noise, epsilon decay and
    n-step folding all line up or the serving split changed training."""
    from d4pg_tpu.distributed.actor import ActorWorker

    store = _published_store()
    actor_cfg = ActorConfig(n_step=3, noise="gaussian", weight_poll_every=4)

    def pool():
        return EnvPool([lambda: PointMassEnv(horizon=20, seed=11)], seed=5)

    legacy_sink, lane_sink = _Capture(), _Capture()
    legacy = ActorWorker("a0", CFG, actor_cfg, pool(), legacy_sink, store,
                         seed=9)
    legacy.run(64)
    lane = VectorActorLane(
        "a0", CFG, actor_cfg, pool(), lane_sink,
        policy=LocalPolicyClient(CFG, actor_cfg, store, seed=9))
    lane.run(64)
    assert legacy.env_steps == lane.env_steps == 64
    a, b = _stream(legacy_sink), _stream(lane_sink)
    for field in a:
        assert a[field].dtype == b[field].dtype
        np.testing.assert_array_equal(a[field], b[field], err_msg=field)


# --------------------------------------------------- batching server ---


def test_server_batches_match_direct_dispatch():
    """Served actions equal a direct ``act_deterministic`` call (within
    float tolerance — padding to a power-of-two bucket must not leak
    into real rows), and concurrent lane requests coalesce into fewer
    dispatches than requests."""
    store = _published_store()
    server = PolicyInferenceServer(CFG, store, batch_window_s=0.05,
                                   max_batch_rows=64)
    clients = [RemotePolicyClient(CFG, ActorConfig(noise="gaussian"),
                                  "127.0.0.1", server.port, lane_id=i,
                                  seed=i, timeout=5.0)
               for i in range(4)]
    try:
        # wait for the refresher to adopt the published snapshot
        deadline = time.monotonic() + 5.0
        while server.serving_stats()["version"] == 0:
            assert time.monotonic() < deadline, "refresher never adopted"
            time.sleep(0.01)
        rng = np.random.default_rng(0)
        obs = [rng.standard_normal((3 + i, 4)).astype(np.float32)
               for i in range(4)]
        got = [None] * 4
        threads = [threading.Thread(
            target=lambda i=i: got.__setitem__(
                i, clients[i].greedy_actions(obs[i])))
            for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        _, params = store.get_if_newer(0)
        for i in range(4):
            want = np.asarray(act_deterministic(CFG, params, obs[i]))
            np.testing.assert_allclose(got[i], want, rtol=1e-5, atol=1e-6)
        stats = server.serving_stats()
        assert stats["rows"] == sum(o.shape[0] for o in obs)
        # 4 requests inside one 50 ms window: genuinely coalesced
        assert stats["batches"] < stats["requests"]
        assert stats["padded_rows"] > 0  # non-pow2 totals hit a bucket
        assert 0 < stats["batch_occupancy"]["p50"] <= 1.0
    finally:
        for c in clients:
            c.close()
        server.close()


class _ScriptedStore:
    """snapshot_ex stub driving the refresher's fence by hand."""

    def __init__(self):
        self.snap = {"params": None, "version": 0, "step": 0,
                     "generation": 0, "published_ts": time.monotonic(),
                     "norm_stats": None}

    def set(self, generation, version, params):
        self.snap.update(generation=generation, version=version,
                         params=params)

    def snapshot_ex(self):
        return dict(self.snap)


def test_fenced_adoption_rejects_version_rewind():
    store = _ScriptedStore()
    server = PolicyInferenceServer(CFG, store, refresh_interval_s=3600.0)
    params = init_state(CFG, jax.random.key(0)).actor_params
    try:
        assert server.refresh_once() is False  # nothing published yet
        store.set(0, 5, params)
        assert server.refresh_once() is True
        # version rewind without a generation bump: NEVER adopted
        store.set(0, 3, params)
        assert server.refresh_once() is False
        s = server.serving_stats()
        assert s["version"] == 5 and s["fenced_rejected"] == 1
        # a generation bump legitimizes a rewound version counter
        store.set(1, 1, params)
        assert server.refresh_once() is True
        s = server.serving_stats()
        assert (s["generation"], s["version"]) == (1, 1)
        assert s["adoptions"] == 2
    finally:
        server.close()


# ----------------------------------------------- degradation ladder ----


def test_no_params_server_yields_counted_warmup():
    server = PolicyInferenceServer(CFG, WeightStore(),
                                   batch_window_s=0.001)
    client = RemotePolicyClient(CFG, ActorConfig(noise="gaussian"),
                                "127.0.0.1", server.port, timeout=5.0)
    try:
        acts = client.actions(np.zeros((3, 4), np.float32))
        assert acts.shape == (3, 2)
        assert (np.abs(acts) <= 1.0).all()
        st = client.stats()
        assert st["no_params"] == 1 and st["warmup_fallbacks"] == 1
        assert st["served"] == 0
    finally:
        client.close()
        server.close()


def test_dead_server_falls_back_to_cached_params():
    """Rung 3: no server at all -> local mu from the weights handle,
    counted, never a stall."""
    store = _published_store()
    # grab a port with nothing listening behind it
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    client = RemotePolicyClient(CFG, ActorConfig(noise="gaussian"),
                                "127.0.0.1", dead_port, timeout=0.2,
                                connect_timeout=0.2, weights=store)
    try:
        obs = np.ones((2, 4), np.float32)
        t0 = time.monotonic()
        acts = client.actions(obs)
        assert time.monotonic() - t0 < 2.0  # bounded, not a stall
        st = client.stats()
        assert st["fallbacks"] == 1 and st["served"] == 0
        _, params = store.get_if_newer(0)
        mu = np.asarray(act_deterministic(CFG, params, obs))
        # greedy fallback + client-side exploration noise stays in range
        assert (np.abs(acts) <= 1.0).all()
        np.testing.assert_allclose(client.greedy_actions(obs), mu,
                                   rtol=1e-5, atol=1e-6)
    finally:
        client.close()


def test_torn_responses_rejected_then_fallback():
    store = _published_store()
    chaos = ServingChaos(torn_response_rate=1.0, seed=2)
    server = PolicyInferenceServer(CFG, store, batch_window_s=0.001,
                                   chaos=chaos)
    client = RemotePolicyClient(CFG, ActorConfig(noise="gaussian"),
                                "127.0.0.1", server.port, timeout=5.0,
                                weights=store, record_ledger=True)
    try:
        deadline = time.monotonic() + 5.0
        while server.serving_stats()["version"] == 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        acts = client.actions(np.zeros((2, 4), np.float32))
        assert acts.shape == (2, 2)
        st = client.stats()
        assert st["torn_rejected"] == 1 and st["served"] == 0
        assert st["fallbacks"] == 1
        assert chaos.torn_injected == 1
        # the chaos oracle in miniature: nothing torn was acted on
        assert client.accepted_req_ids == set()
    finally:
        client.close()
        server.close()


# ------------------------------------------------------ chaos + gate ---


@pytest.mark.fleet
def test_serving_chaos_smoke():
    """A small end-to-end chaos run must pass all three gating oracles
    — torn-acceptance ledger, trace orphans, lock hierarchy — recover
    from the server kill (MTTR measured), and count its degradation
    instead of stalling. The full-size version is the bench artifact's
    serving block."""
    from d4pg_tpu.fleet.serving_chaos import (
        ServingChaosConfig,
        run_serving_chaos,
    )

    from d4pg_tpu.obs.registry import REGISTRY

    crashes0 = REGISTRY.counter("threads.contained_crashes").value
    rep = run_serving_chaos(ServingChaosConfig(
        n_lanes=2, envs_per_lane=2, duration_s=1.5, server_kills=1,
        torn_prob=0.1, seed=3))
    assert rep["server_kills"] == 1
    # chaos is injected through narrow, expected-error paths; the broad
    # top-frame containments must never fire during a clean run
    assert REGISTRY.counter("threads.contained_crashes").value == crashes0
    assert rep["mttr_s"] and rep["mttr_s"][0] is not None
    assert rep["torn"]["injected"] > 0
    assert rep["torn"]["accepted"] == 0
    assert rep["trace"]["orphans"] == 0
    assert rep["hierarchy_violations"] == 0
    assert rep["lanes_converged"] == 2
    assert rep["served"] > 0 and rep["env_steps"] > 0
    # the kill window degraded (counted), never stalled the lanes
    assert (rep["fallbacks"] + rep["warmup_fallbacks"]
            + rep["timeouts"] + rep["wire_errors"]) > 0
    assert rep["ingest"]["env_steps"] > 0  # transitions rode the wire


@pytest.mark.obs
def test_fleet_artifact_serving_schema():
    """The newest committed fleet artifact must carry the serving block:
    the lane sweep, the batched-vs-unbatched pair with batched winning
    on actions/s at equal lane count, and a >=1-server-kill chaos row
    with all oracles clean — a later PR that drops any of it fails
    tier-1 here."""
    arts = sorted(glob.glob(os.path.join(
        REPO_ROOT, "docs", "evidence", "fleet", "fleet_*.json")))
    assert arts, "no committed fleet artifact"
    with open(arts[-1]) as f:
        artifact = json.load(f)
    s = artifact.get("serving")
    assert s, "newest fleet artifact lost its serving block"
    assert s["metric"] == "fleet_serving" and s["schema"] == 1
    assert len(s["sweep"]) >= 3
    for row in s["sweep"]:
        assert row["actions_per_sec"] > 0
        assert row["trace_orphans"] == 0
        assert row["hierarchy_violations"] == 0
        for pct in ("p50", "p95", "p99"):
            assert row["latency_ms"][pct] is not None
    # the continuous-batching claim, measured on the same wire
    pair = s["batching"]
    assert pair["batched_actions_per_sec"] > 0
    assert pair["unbatched_actions_per_sec"] > 0
    assert pair["speedup"] is not None and pair["speedup"] > 1.0
    chaos = s["chaos"]
    assert chaos["server_kills"] >= 1
    assert chaos["mttr_s"] and all(m is not None for m in chaos["mttr_s"])
    assert chaos["torn"]["injected"] >= 1 and chaos["torn"]["accepted"] == 0
    assert chaos["trace"]["orphans"] == 0
    assert chaos["hierarchy_violations"] == 0
