"""Config/CLI, metrics, and checkpoint/resume tests."""

import csv
import os

import jax
import numpy as np
import pytest

from d4pg_tpu.config import ExperimentConfig, parse_args
from d4pg_tpu.io import CheckpointManager, CsvLogger, MetricsBus
from d4pg_tpu.learner import D4PGConfig, init_state, make_update
from d4pg_tpu.replay.uniform import TransitionBatch


def test_parse_args_defaults_and_overrides():
    cfg = parse_args([])
    assert cfg.env == "Pendulum-v1" and cfg.prioritized_replay and not cfg.her
    cfg = parse_args(["--env", "point", "--p_replay", "0", "--her", "1",
                      "--bsize", "128", "--rmsize", "999", "--n_eps", "3",
                      "--adam_b2", "0.9"])
    assert cfg.env == "point" and not cfg.prioritized_replay and cfg.her
    assert cfg.batch_size == 128 and cfg.memory_size == 999
    assert cfg.n_epochs == 3 and cfg.adam_b2 == 0.9


def test_run_name_encodes_config():
    """Parity with the reference's run-dir naming (main.py:59-64)."""
    cfg = ExperimentConfig(env="Pendulum-v1", prioritized_replay=True, her=False,
                           n_steps=3, n_workers=2)
    name = cfg.run_name()
    assert "Pendulum-v1" in name and "PER" in name and "HER" not in name
    assert "3N" in name and "2Workers" in name


def test_preset_resolution():
    cfg = ExperimentConfig(env="Pendulum-v1").resolve()
    assert cfg.v_min == -100.0 and cfg.v_max == 0.0 and cfg.reward_scale == 0.1
    # explicit values win over presets
    cfg = ExperimentConfig(env="Pendulum-v1", v_min=-7.0, v_max=7.0).resolve()
    assert cfg.v_min == -7.0 and cfg.v_max == 7.0


def test_csv_logger(tmp_path):
    path = str(tmp_path / "returns.csv")
    log = CsvLogger(path, ["a", "b"])
    log.write(1, {"a": 1.5, "b": 2.5})
    log.write(2, {"a": 3.0})
    log.close()
    rows = list(csv.reader(open(path)))
    assert rows[0] == ["1", "1.5", "2.5"]
    assert rows[1] == ["2", "3.0", ""]


def test_metrics_bus_fanout(tmp_path):
    got = []

    class Sink:
        def write(self, step, metrics):
            got.append((step, dict(metrics)))

        def close(self):
            pass

    bus = MetricsBus([Sink()])
    bus.log(3, {"x": 1.0})
    bus.close()
    assert got == [(3, {"x": 1.0})]


def test_checkpoint_roundtrip_and_resume(tmp_path, rng):
    """Full-state save -> restore -> identical params AND identical
    continued training (the resume capability the reference lacks, C20)."""
    config = D4PGConfig(obs_dim=3, act_dim=1, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    state = init_state(config, jax.random.key(0))
    update = make_update(config, donate=False, use_is_weights=False)
    done = np.zeros(8, np.float32)
    batch = TransitionBatch(
        obs=rng.standard_normal((8, 3)).astype(np.float32),
        action=rng.uniform(-1, 1, (8, 1)).astype(np.float32),
        reward=rng.standard_normal(8).astype(np.float32),
        next_obs=rng.standard_normal((8, 3)).astype(np.float32),
        done=done,
        discount=(0.99 * (1 - done)).astype(np.float32),
    )
    for _ in range(3):
        state, _ = update(state, batch)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(state, extra={"env_steps": 123})
    mgr.wait()
    assert mgr.latest_step == 3

    template = init_state(config, jax.random.key(99))
    restored, extra = mgr.restore(template)
    assert extra["env_steps"] == 123
    assert int(restored.step) == 3
    for a, b in zip(jax.tree_util.tree_leaves(state.actor_params),
                    jax.tree_util.tree_leaves(restored.actor_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # continued training from the restore matches continued training live
    s_live, _ = update(state, batch)
    s_resumed, _ = update(restored, batch)
    for a, b in zip(jax.tree_util.tree_leaves(s_live.critic_params),
                    jax.tree_util.tree_leaves(s_resumed.critic_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_replay_state_roundtrip_host_and_per(rng):
    """Replay checkpointing (SURVEY.md §5 elastic recovery): contents,
    ring cursor, PER leaf priorities and max_priority all survive a
    state_dict round trip — on the host buffer and the fused device
    buffer alike."""
    from d4pg_tpu.replay import PrioritizedReplayBuffer
    from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

    def fill(buf):
        done = np.zeros(40, np.float32)
        buf.add(TransitionBatch(
            obs=rng.standard_normal((40, 3)).astype(np.float32),
            action=rng.uniform(-1, 1, (40, 1)).astype(np.float32),
            reward=np.arange(40, dtype=np.float32),
            next_obs=rng.standard_normal((40, 3)).astype(np.float32),
            done=done,
            discount=np.full(40, 0.99, np.float32)))

    src = PrioritizedReplayBuffer(64, 3, 1, alpha=0.6)
    fill(src)
    src.update_priorities(np.arange(10), np.linspace(1, 5, 10))
    dst = PrioritizedReplayBuffer(64, 3, 1, alpha=0.6)
    dst.load_state_dict(src.state_dict())
    assert dst.size == src.size and dst.head == src.head
    np.testing.assert_array_equal(dst.reward[:40], src.reward[:40])
    np.testing.assert_allclose(dst._trees.get(np.arange(40)),
                               src._trees.get(np.arange(40)))
    assert dst.max_priority == src.max_priority
    # min tree of unwritten slots stays neutral: sampling still works
    assert np.isfinite(dst.is_weights(np.arange(5), 0.5)).all()

    fsrc = FusedDeviceReplay(64, 3, 1, alpha=0.6)
    fill(fsrc)
    fsrc.drain()
    fdst = FusedDeviceReplay(64, 3, 1, alpha=0.6)
    fdst.load_state_dict(fsrc.state_dict())
    assert fdst.size == 40 and fdst.head == fsrc.head
    np.testing.assert_array_equal(np.asarray(fdst.storage.reward[:40]),
                                  np.asarray(fsrc.storage.reward[:40]))
    np.testing.assert_allclose(np.asarray(fdst.trees.sum_tree),
                               np.asarray(fsrc.trees.sum_tree))


def test_train_resume_with_replay(tmp_path):
    """--checkpoint_replay 1 + --resume 1: the second run restores the
    buffer (no re-warmup) and continues from the checkpointed step."""
    from d4pg_tpu.train import train

    common = dict(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=4,
        eval_trials=1, batch_size=16, memory_size=2000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0, checkpoint_replay=True,
        checkpoint_replay_every=1,
    )
    m1 = train(ExperimentConfig(**common))
    m2 = train(ExperimentConfig(**common, resume=True))
    assert np.isfinite(m2["critic_loss"])
    assert m2["env_steps"] > m1["env_steps"]
    # the restored buffer skips the second warmup: only the two collect
    # phases (~80 env steps) are added, not another ~100-step warmup
    assert m2["env_steps"] - m1["env_steps"] < 100


def test_checkpoint_restore_empty_dir(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    config = D4PGConfig(obs_dim=3, act_dim=1, n_atoms=11, hidden=(8,))
    with pytest.raises(FileNotFoundError):
        mgr.restore(init_state(config, jax.random.key(0)))
    mgr.close()


def test_train_entrypoint_end_to_end(tmp_path):
    """Tiny full run through the CLI path on the fake env (no MuJoCo)."""
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
        n_cycles=1, episodes_per_cycle=1, train_steps_per_cycle=3,
        eval_trials=1, batch_size=16, memory_size=2000,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-5.0, v_max=0.0,
    )
    metrics = train(cfg)
    assert "avg_test_reward" in metrics and np.isfinite(metrics["critic_loss"])
    run_dir = os.path.join(str(tmp_path), cfg.run_name())
    assert os.path.exists(os.path.join(run_dir, "returns.csv"))
    assert os.path.isdir(os.path.join(run_dir, "ckpt"))


def test_full_train_determinism(tmp_path):
    """System-level determinism (SURVEY.md §5): two identical sync-mode
    runs produce identical eval trajectories — the property the reference's
    hogwild design cannot have."""
    from d4pg_tpu.train import train

    def run(tag):
        # concurrent_eval=False: with the background evaluator, WHICH cycle
        # row an eval result lands in depends on thread timing; inline eval
        # keeps the CSV bitwise-reproducible.
        cfg = ExperimentConfig(
            env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
            n_cycles=2, episodes_per_cycle=2, train_steps_per_cycle=4,
            eval_trials=2, batch_size=16, memory_size=2000,
            log_dir=str(tmp_path / tag), hidden=(16, 16), n_atoms=11,
            v_min=-5.0, v_max=0.0, seed=123, concurrent_eval=False,
        )
        train(cfg)
        csv = os.path.join(str(tmp_path / tag), cfg.run_name(), "returns.csv")
        return open(csv).read()

    assert run("a") == run("b")


def test_strict_reference_mode():
    """--strict_reference 1 = the reference's own hyperparameters in one
    flag (VERDICT r1 #10)."""
    from d4pg_tpu.config import parse_args

    cfg = parse_args(["--env", "Pendulum-v1", "--strict_reference", "1"]).resolve()
    assert cfg.v_min == -300.0 and cfg.v_max == 0.0  # main.py:86-88
    assert cfg.reward_scale == 1.0
    assert cfg.adam_b1 == 0.9 and cfg.adam_b2 == 0.9  # shared_adam.py:4
    assert cfg.lr_actor == 1e-3 and cfg.lr_critic == 1e-3
    assert cfg.updates_per_dispatch == 1
    # default mode keeps the documented divergence
    d = parse_args(["--env", "Pendulum-v1"]).resolve()
    assert d.v_min == -100.0 and d.reward_scale == 0.1


def test_host_replay_sidecar_staleness_rules(tmp_path):
    """The step-stamped replay sidecar: an OLDER snapshot than the
    restored state is accepted (stale rows are valid experience; the old
    strict-equality rule emptied the buffer whenever the replay cadence
    was coarser than the state cadence), a NEWER one is refused (the
    save site commits state before the sidecar rename, so ahead-of-state
    means mixed run dirs)."""
    from d4pg_tpu.train import _load_host_replay, _save_host_replay

    snap = {"rows": "payload"}
    _save_host_replay(str(tmp_path), 0, step=100, snap=snap)
    # exact match
    got, step = _load_host_replay(str(tmp_path), 0, step=100)
    assert got == snap and step == 100
    # stale (older than state): accepted
    got, step = _load_host_replay(str(tmp_path), 0, step=160)
    assert got == snap and step == 100
    # ahead of state: refused
    got, step = _load_host_replay(str(tmp_path), 0, step=40)
    assert got is None and step == -1
    # absent
    got, step = _load_host_replay(str(tmp_path), 7, step=100)
    assert got is None and step == -1


def test_single_host_resume_reads_stale_sidecar(tmp_path):
    """Resume restores the buffer from the sidecar even when the replay
    cadence was coarser than the state cadence — the round-4 failure
    mode: the LATEST state checkpoint used to be the only replay source,
    so 4 out of 5 resumes silently restarted with an empty buffer."""
    import re

    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import train

    def run(resume):
        cfg = ExperimentConfig(
            env="point", max_steps=20, num_envs=2, warmup=100, n_epochs=1,
            n_cycles=4, episodes_per_cycle=1, train_steps_per_cycle=8,
            eval_trials=1, batch_size=16, memory_size=2000,
            log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
            v_min=-5.0, v_max=0.0, checkpoint_replay=True,
            # replay saved only every 3rd save; state saved every cycle —
            # the LAST state checkpoint (cycle 4) has no replay save
            checkpoint_replay_every=3, resume=resume,
        )
        return train(cfg)

    run(False)
    run_dirs = [d for d in os.listdir(tmp_path) if d.startswith("exp_")]
    sidecar = os.path.join(tmp_path, run_dirs[0], "replay_p0.pkl")
    assert os.path.exists(sidecar)
    import io as _io
    from contextlib import redirect_stdout

    buf = _io.StringIO()
    with redirect_stdout(buf):
        run(True)
    out = buf.getvalue()
    m = re.search(r"resumed from step (\d+) \((\d+) env steps, (\d+) replay rows", out)
    assert m, out[-2000:]
    assert int(m.group(1)) == 32  # restored latest state (4 cycles x 8)
    assert int(m.group(3)) > 0   # buffer restored from the STALE sidecar
    assert "steps behind the restored state" in out
