"""Backend probe / fallback policy tests (probe.py, entry-point hardening)."""

import os

import pytest

from d4pg_tpu import probe


def test_probe_platform_reports_cpu_or_accel():
    # inside the test env the child resolves SOME platform; the tri-state
    # contract is what matters (never raises, never hangs past timeout)
    status = probe.probe_platform(timeout=120.0)
    assert status in ("accel", "cpu", "dead")


def test_ensure_backend_env_overrides(monkeypatch):
    monkeypatch.setenv("D4PG_PLATFORM", "accel")
    assert probe.ensure_backend() == "accel"
    # cpu override must not probe (instant) and must report 'cpu-forced'
    monkeypatch.setenv("D4PG_PLATFORM", "cpu")
    assert probe.ensure_backend() == "cpu-forced"


def test_ensure_backend_wedged_forces_cpu(monkeypatch):
    monkeypatch.delenv("D4PG_PLATFORM", raising=False)
    monkeypatch.setattr(probe, "probe_platform", lambda timeout=0: "dead")
    assert probe.ensure_backend() == "cpu-wedged"
    import jax

    # conftest already pins cpu; the point is the call went through the
    # forcing path without raising
    assert jax.config.jax_platforms == "cpu"


def test_accelerator_alive_matches_probe(monkeypatch):
    monkeypatch.setattr(probe, "probe_platform", lambda timeout=0: "accel")
    assert probe.accelerator_alive()
    monkeypatch.setattr(probe, "probe_platform", lambda timeout=0: "cpu")
    assert not probe.accelerator_alive()
