"""gymnasium-robotics compat shim + env-family contract tests.

BASELINE.md config #5 (Adroit / Shadow-Hand manipulation) and the HER
family (Fetch) ship MuJoCo-2-era MJCF that MuJoCo 3 rejects; the
``robotics_compat`` shim makes them loadable. Skipped when the packages
are absent so the suite stays runnable on slim images.
"""

import numpy as np
import pytest

try:
    import gymnasium as gym
    import gymnasium_robotics  # noqa: F401

    _HAVE = True
except Exception:
    _HAVE = False

pytestmark = pytest.mark.skipif(
    not _HAVE, reason="gymnasium_robotics unavailable"
)


@pytest.fixture(scope="module")
def registry():
    from d4pg_tpu.envs.robotics_compat import install

    install()
    gym.register_envs(gymnasium_robotics)
    return gym


def test_apirate_stripping_preserves_other_attrs(tmp_path):
    from d4pg_tpu.envs import robotics_compat as rc

    src = tmp_path / "assets"
    src.mkdir()
    (src / "model.xml").write_bytes(
        b'<mujoco><option apirate="200" timestep="0.002"/></mujoco>'
    )
    (src / "clean.xml").write_bytes(b"<mujoco/>")
    assert rc._needs_patch(str(src))
    shadow = rc._shadow_dir(str(src))
    patched = (pytest.importorskip("pathlib").Path(shadow) / "model.xml").read_bytes()
    assert b"apirate" not in patched
    assert b'timestep="0.002"' in patched


def test_adroit_loads_and_steps(registry):
    env = registry.make("AdroitHandDoor-v1")
    obs, _ = env.reset(seed=0)
    assert obs.shape == (39,)
    assert env.action_space.shape == (28,)  # high-dim action, config #5
    obs2, r, term, trunc, info = env.step(
        np.zeros(env.action_space.shape, np.float32)
    )
    assert np.isfinite(r)
    env.close()


def test_shadow_hand_goal_env_contract(registry):
    env = registry.make("HandReach-v3")
    obs, _ = env.reset(seed=0)
    assert set(obs) >= {"observation", "achieved_goal", "desired_goal"}
    obs2, r, term, trunc, info = env.step(
        np.zeros(env.action_space.shape, np.float32)
    )
    assert "is_success" in info
    # HER needs a vectorizable compute_reward (main.py:177 contract)
    ag = np.stack([obs["achieved_goal"]] * 4)
    dg = np.stack([obs["desired_goal"]] * 4)
    rr = env.unwrapped.compute_reward(ag, dg, {})
    assert np.asarray(rr).shape == (4,)
    env.close()


def test_fetch_reach_goal_env(registry):
    env = registry.make("FetchReach-v4")
    obs, _ = env.reset(seed=0)
    assert obs["achieved_goal"].shape == (3,)
    r = env.unwrapped.compute_reward(
        obs["achieved_goal"], obs["desired_goal"], {}
    )
    assert float(r) in (-1.0, 0.0)
    env.close()


def test_make_env_fn_resolves_robotics_ids():
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import make_env_fn

    cfg = ExperimentConfig(env="AdroitHandDoor-v1")
    env = make_env_fn(cfg, seed=0)()
    assert env.action_space.shape == (28,)
    env.close()
