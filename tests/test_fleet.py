"""Fleet-plane tests: the chaos harness itself.

Tier-1 scope: an N=8 chaos-enabled smoke (seeded, seconds), bit-for-bit
reproducibility of the seeded fault script, determinism of the chaos
primitives, and the degradation bookkeeping (every lost row lands in a
named counter). The wide sweeps (N up to 256) are ``slow``; their real
run is the committed ``docs/evidence/fleet/`` artifact from
``python bench.py --fleet``.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from d4pg_tpu.fleet import (
    ActorChaos,
    ChaosConfig,
    ChaosPolicy,
    FleetConfig,
    FleetHarness,
    StallGate,
    run_sweep,
    synthetic_block,
)

# The tier-1 chaos mix: every fault kind enabled, scaled so an N=8 x
# 12-tick run still exercises drops, delays, crashes AND the stall gate.
SMOKE_CHAOS = ChaosConfig(
    drop_prob=0.1,
    delay_prob=0.2, delay_min_s=0.001, delay_max_s=0.005,
    crash_prob=0.05, restart_delay_s=0.3,
    receiver_stall_s=0.1, stall_every_s=0.4,
    seed=7,
)


def _smoke_config(**overrides) -> FleetConfig:
    base = dict(
        n_actors=8, max_ticks=12, rows_per_sec=400.0, block_rows=16,
        obs_dim=24, act_dim=4, capacity=20_000, heartbeat_timeout=0.5,
        evict_every_s=0.1, send_timeout=0.5, chaos=SMOKE_CHAOS,
    )
    base.update(overrides)
    return FleetConfig(**base)


def test_chaos_stream_deterministic():
    """Decision i of actor k depends only on (seed, k, i): two streams
    built from the same config replay the identical fault script, and a
    different actor index yields a different (decorrelated) one."""
    a = ActorChaos(SMOKE_CHAOS, 3, "a3")
    b = ActorChaos(SMOKE_CHAOS, 3, "a3")
    other = ActorChaos(SMOKE_CHAOS, 4, "a3")
    seq_a = [a.next() for _ in range(200)]
    seq_b = [b.next() for _ in range(200)]
    seq_o = [other.next() for _ in range(200)]
    assert seq_a == seq_b
    assert seq_a != seq_o
    kinds = {ev.kind for ev in seq_a}
    assert kinds == {"ok", "drop", "delay", "crash"}  # all faults live
    for ev in seq_a:
        if ev.kind == "delay":
            assert SMOKE_CHAOS.delay_min_s <= ev.arg <= SMOKE_CHAOS.delay_max_s


def test_stall_schedule_deterministic_and_bounded():
    policy = ChaosPolicy(SMOKE_CHAOS)
    sched = policy.stall_schedule(3.0)
    assert sched == policy.stall_schedule(3.0)
    assert sched, "stalls enabled but schedule empty"
    assert all(0 < t < 3.0 and d == SMOKE_CHAOS.receiver_stall_s
               for t, d in sched)
    assert ChaosPolicy(ChaosConfig()).stall_schedule(10.0) == []


def test_chaos_config_validation():
    with pytest.raises(ValueError):
        ChaosConfig(drop_prob=1.5)
    with pytest.raises(ValueError):
        ChaosConfig(delay_min_s=0.2, delay_max_s=0.1)
    assert not ChaosConfig().enabled()
    assert SMOKE_CHAOS.enabled()


def test_stall_gate_bounded_wait():
    gate = StallGate()
    assert gate.wait(timeout=0.1)  # open by default
    gate.stall()
    t0 = time.monotonic()
    assert not gate.wait(timeout=0.05)  # bounded, not a deadlock
    assert time.monotonic() - t0 < 1.0
    gate.resume()
    assert gate.wait(timeout=0.1)
    assert gate.stalls == 1


def test_fleet_smoke_n8_with_chaos():
    """The tier-1 acceptance smoke: 8 lanes, every fault kind enabled,
    seeded, seconds of wall clock — the plane must ingest rows, count
    every loss, recover from crashes, and finish without a deadlock."""
    result = FleetHarness(_smoke_config()).run()
    assert result["deadlocks"] == 0
    assert result["rows_per_sec"] > 0
    assert result["rows_inserted"] > 0
    assert result["ticks"] == 8 * 12
    # accounting closes: every attempted row was inserted or counted lost
    # (TCP frames accepted into a dying receiver's buffer are the only
    # non-counted loss mode, and the receiver here outlives the lanes)
    drops = result["drops"]
    assert result["rows_inserted"] + drops["backpressure_rows"] \
        + drops["shed_rows"] <= result["rows_attempted"]
    # the seeded script fired every fault kind at this size (seed-pinned)
    assert result["crashes"] > 0
    assert drops["chaos_rows"] > 0
    assert result["recovery"]["n"] > 0  # crash -> delivery measured
    assert result["receiver_stalls"] > 0
    lat = result["send_latency_ms"]
    assert lat["n"] > 0 and lat["p99"] >= lat["p50"] > 0
    # the smoke runs with lock-hierarchy assertions armed (record mode):
    # zero violations, and per-lock contention counters in the artifact
    locks = result["locks"]
    assert locks["hierarchy_violations"] == 0
    assert locks["violation_samples"] == []
    for tier in ("service", "shard", "commit"):
        per = locks["per_lock"][tier]
        assert per["acquisitions"] > 0
        assert per["wait_ns"] >= 0 and per["max_hold_ns"] > 0


def test_fleet_seeded_run_reproducible_bitwise():
    """Acceptance bar: seeded chaos runs reproduce bit-for-bit at the
    harness level — the full fault script (actor, tick, kind, float arg)
    is identical across two runs, as are the script-derived counters."""
    a = FleetHarness(_smoke_config()).run()
    b = FleetHarness(_smoke_config()).run()
    assert a["chaos_log"] == b["chaos_log"]
    assert a["crashes"] == b["crashes"]
    assert a["drops"]["chaos_rows"] == b["drops"]["chaos_rows"]
    assert a["ticks"] == b["ticks"]
    # ...and a different seed yields a different script
    c = FleetHarness(_smoke_config(
        chaos=dataclasses.replace(SMOKE_CHAOS, seed=8))).run()
    assert c["chaos_log"] != a["chaos_log"]


def test_fleet_eviction_and_readmission_under_crash():
    """A crashed lane whose outage exceeds the heartbeat timeout is
    evicted; its post-restart stream re-admits it (service-side recovery
    interval recorded)."""
    chaos = ChaosConfig(crash_prob=0.2, restart_delay_s=0.4, seed=3)
    result = FleetHarness(_smoke_config(
        chaos=chaos, max_ticks=20, heartbeat_timeout=0.25,
        evict_every_s=0.05)).run()
    assert result["crashes"] > 0
    assert result["evictions"] > 0
    assert result["readmissions"] > 0
    assert result["service_recovery"]["n"] > 0
    assert result["service_recovery"]["mean_s"] > 0
    assert result["deadlocks"] == 0


def test_synthetic_block_shapes_and_determinism():
    a = synthetic_block(16, 24, 4, seed=5)
    b = synthetic_block(16, 24, 4, seed=5)
    assert a.obs.shape == (16, 24) and a.action.shape == (16, 4)
    np.testing.assert_array_equal(a.obs, b.obs)
    assert a.obs.dtype == np.float32


def test_fleet_process_mode_small():
    """The optional subprocess mode: same lane loop, real processes. Kept
    tiny (2 lanes, no chaos) — it pays a spawn+import per lane."""
    cfg = _smoke_config(n_actors=2, max_ticks=4, mode="process",
                        chaos=ChaosConfig(seed=1),
                        connect_stagger_s=0.05)
    result = FleetHarness(cfg).run()
    assert result["mode"] == "process"
    assert result["deadlocks"] == 0
    assert result["rows_inserted"] == 2 * 4 * 16  # no chaos: all delivered
    assert result["chaos_log"] and all(
        ev[2] == "ok" for ev in result["chaos_log"])


def test_fleet_config_validation():
    with pytest.raises(ValueError):
        FleetConfig(mode="coroutine")
    assert FleetConfig(n_actors=4).demand_rows_per_sec() == 4 * 20.0


def test_fleet_smoke_sharded_k2():
    """The sharded receiver under the full tier-1 chaos mix: K=2 ingest
    shards, v2 raw frames (codec auto-resolves), every fault kind firing
    — zero deadlocks, zero merge order-breaks, and every shard's
    counters consistent with the rows it owned."""
    result = FleetHarness(_smoke_config(ingest_shards=2)).run()
    assert result["ingest_shards"] == 2
    assert result["codec"] == "raw"  # auto resolves to the v2 plane
    assert result["deadlocks"] == 0
    assert result["order_breaks"] == 0
    assert result["decode_errors"] == 0
    assert result["rows_inserted"] > 0
    assert result["ticks"] == 8 * 12
    assert result["rows_per_sec_per_shard"] == pytest.approx(
        result["rows_per_sec"] / 2, abs=0.1)
    # K=2 exercises the full tier stack under chaos — still zero
    # hierarchy violations, and the shard conditions saw real traffic
    assert result["locks"]["hierarchy_violations"] == 0
    assert result["locks"]["per_lock"]["shard"]["acquisitions"] > 0
    shards = result["per_shard"]
    assert [s["shard"] for s in shards] == [0, 1]
    # per-shard admission accounting covers every delivered row
    assert sum(s["rows_in"] for s in shards) >= result["rows_inserted"]
    drops = result["drops"]
    assert result["rows_inserted"] + drops["backpressure_rows"] \
        + drops["shed_rows"] <= result["rows_attempted"]
    assert result["crashes"] > 0 and drops["chaos_rows"] > 0


def _scripted_feed(n_lanes: int, ticks: int, block_rows: int = 8,
                   obs_dim: int = 6, act_dim: int = 2):
    """The deterministic K-equivalence feed: the SAME seeded fleet script
    (chaos decides which (lane, tick) blocks deliver), serialized in
    canonical (tick, lane) order. Lane k's tick t block is seeded by
    (k, t), so the feed is bit-reproducible."""
    policy = ChaosPolicy(SMOKE_CHAOS)
    streams = [policy.actor_stream(k, f"lane-{k}") for k in range(n_lanes)]
    feed = []
    for t in range(ticks):
        for k, chaos in enumerate(streams):
            ev = chaos.next()
            if ev.kind in ("ok", "delay"):  # delivered blocks only
                feed.append((k, synthetic_block(
                    block_rows, obs_dim, act_dim, seed=1000 * k + t)))
    return feed


def test_fleet_k2_bitwise_replay_equivalence_vs_k1():
    """Acceptance bar: the same seeded fleet script through a K=1 and a
    K=2 service lands the IDENTICAL final buffer — same bytes in the
    same slots, same env-step count — because the sharded plane's merge
    commits in admission-ticket order (docs/architecture.md
    "merge-commit ordering rules")."""
    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.replay.uniform import ReplayBuffer

    feed = _scripted_feed(n_lanes=4, ticks=30)
    assert len(feed) > 50  # the script actually delivered a fleet's worth
    s1 = ReplayService(ReplayBuffer(100_000, 6, 2))
    s2 = ReplayService(ReplayBuffer(100_000, 6, 2), num_ingest_shards=2)
    for k, block in feed:
        s1.add(block, actor_id=f"lane-{k}")
        s2.add(block, actor_id=f"lane-{k}", shard=k % 2)
    s1.flush(timeout=10.0)
    s2.flush(timeout=10.0)
    assert s1.env_steps == s2.env_steps == 8 * len(feed)
    assert len(s1) == len(s2)
    for field in ("obs", "action", "reward", "next_obs", "done",
                  "discount"):
        np.testing.assert_array_equal(
            getattr(s1.buffer, field), getattr(s2.buffer, field))
    # counter-total equivalence (obs plane, no-double-count contract):
    # the unified row ledger must agree bitwise between the K=1 and K=2
    # planes — admitted == committed == env_steps on a clean feed, with
    # NO contribution from which internal path (drain vs direct-stage)
    # carried the rows
    st1, st2 = s1.ingest_stats(), s2.ingest_stats()
    assert st2["order_breaks"] == 0
    for key in ("env_steps", "rows_committed", "sheds", "shed_rows",
                "decode_errors", "admit_fails"):
        assert st1[key] == st2[key], key
    rows_in1 = sum(p["rows_in"] for p in st1["per_shard"])
    rows_in2 = sum(p["rows_in"] for p in st2["per_shard"])
    assert rows_in1 == rows_in2 == st1["rows_committed"] == 8 * len(feed)
    s1.close()
    s2.close()


def test_fleet_actor_mode_smoke():
    """The real-actor lane mode (ROADMAP gap: "harness drives the
    transport slice"): N=2 lanes each spawn an actual ``actor_main``
    subprocess — env pool, policy inference, live weight pulls — against
    the harness's receiver + weight server, through the sharded (K=2)
    ingest plane. Rows counted by the service must equal the env steps
    the actors report (n-step folding holds a tail back per env)."""
    cfg = _smoke_config(n_actors=2, max_ticks=8, mode="actor",
                        ingest_shards=2, chaos=ChaosConfig(seed=1),
                        send_timeout=5.0, heartbeat_timeout=30.0)
    result = FleetHarness(cfg).run()
    assert result["mode"] == "actor"
    assert result["deadlocks"] == 0
    assert len(result["lane_env_steps"]) == 2
    # 8 ticks x 2 envs per lane of real interaction
    assert all(s == 16 for s in result["lane_env_steps"])
    # every delivered row is real actor data; the n-step folder (n=2)
    # holds a warmup tail back per env, so inserted < env steps but must
    # cover the bulk of the interaction
    assert 0 < result["rows_inserted"] <= sum(result["lane_env_steps"])
    assert result["rows_inserted"] >= sum(result["lane_env_steps"]) // 2
    assert result["ingest"]["order_breaks"] == 0


@pytest.mark.slow
@pytest.mark.fleet
def test_shard_sweep_slow():
    """A bounded K ∈ {1, 2} shard sweep through the real sweep runner
    (the full K ∈ {1, 2, 4} x N=256 version is ``python bench.py
    --fleet``; its artifact is committed under docs/evidence/fleet/)."""
    from d4pg_tpu.fleet import shard_sweep

    artifact = shard_sweep(ks=(1, 2), n_actors=16, duration_s=2.0,
                           rows_per_sec=200.0, chaos=SMOKE_CHAOS,
                           obs_dim=24, act_dim=4, capacity=50_000,
                           block_rows=16, heartbeat_timeout=0.5,
                           evict_every_s=0.1, send_timeout=0.5)
    assert [r["ingest_shards"] for r in artifact["sweep"]] == [1, 2]
    assert [r["codec"] for r in artifact["sweep"]] == ["npz", "raw"]
    for row in artifact["sweep"]:
        assert row["deadlocks"] == 0
        assert row["rows_per_sec"] > 0
        assert row["locks"]["hierarchy_violations"] == 0
    scaling = artifact["scaling"]
    assert scaling[0]["speedup_vs_k1"] == 1.0
    assert all(s["vs_ceiling"] is not None for s in scaling)
    # the K-sweep's lock-wait attribution column is populated per K
    assert all(s["lock_wait_ms"] is not None
               and s["hierarchy_violations"] == 0 for s in scaling)


@pytest.mark.slow
@pytest.mark.fleet
def test_fleet_sweep_slow():
    """A bounded two-point sweep through the real sweep runner (the full
    {8..256} x 10 s version is ``python bench.py --fleet``; its artifact
    is committed under docs/evidence/fleet/)."""
    artifact = run_sweep(ns=(8, 32), duration_s=2.0,
                         chaos=SMOKE_CHAOS, obs_dim=24, act_dim=4,
                         capacity=50_000, rows_per_sec=100.0,
                         block_rows=16, heartbeat_timeout=0.5,
                         evict_every_s=0.1, send_timeout=0.5)
    assert [row["n_actors"] for row in artifact["sweep"]] == [8, 32]
    for row in artifact["sweep"]:
        assert row["deadlocks"] == 0
        assert row["rows_per_sec"] > 0
        assert "chaos_log" not in row  # stripped: regenerable from seed
        assert set(row["drops"]) == {"chaos_rows", "backpressure_rows",
                                     "shed_batches", "shed_rows"}
    assert artifact["metric"] == "fleet_rows_per_sec"
    assert artifact["config"]["chaos"]["seed"] == SMOKE_CHAOS.seed


def test_bench_fleet_entrypoint_importable():
    """bench.bench_fleet is the integration point the artifact pipeline
    calls; it must resolve without an accelerator backend."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert callable(bench.bench_fleet)


def test_fleet_analysis_table_and_plot(tmp_path):
    """actor_scaling renders the sweep artifact as table + PNG."""
    from d4pg_tpu.analysis.actor_scaling import fleet_table, plot_fleet

    artifact = run_sweep(ns=(4,), duration_s=0.0, chaos=SMOKE_CHAOS,
                         max_ticks=4, obs_dim=24, act_dim=4,
                         capacity=10_000, rows_per_sec=200.0,
                         block_rows=8, heartbeat_timeout=0.5,
                         evict_every_s=0.1, send_timeout=0.5)
    table = fleet_table(artifact)
    assert "rows/s" in table and "4" in table
    out = plot_fleet(artifact, str(tmp_path / "fleet.png"))
    import os

    assert os.path.getsize(out) > 0


def test_stop_event_interrupts_lanes():
    """An externally-set stop event ends a duration-mode run early —
    lanes are interruptible mid-sleep (no join timeouts burned)."""
    cfg = _smoke_config(max_ticks=None, duration_s=0.5,
                        chaos=ChaosConfig(seed=0), rows_per_sec=20.0)
    t0 = time.monotonic()
    result = FleetHarness(cfg).run()
    assert time.monotonic() - t0 < 15.0
    assert result["deadlocks"] == 0
    assert threading.active_count() < 100  # lanes actually exited
