"""Test configuration: force the CPU backend with 8 virtual XLA devices.

Per SURVEY.md §4, multi-host/multi-chip behavior is tested on a simulated
8-device CPU mesh (the driver separately dry-runs the multichip path). These
env vars must be set before the first ``import jax`` anywhere in the test
process, which pytest guarantees by importing conftest first.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
