"""Test configuration: force the CPU backend with 8 virtual XLA devices.

Per SURVEY.md §4, multi-host/multi-chip behavior is tested on a simulated
8-device CPU mesh (the driver separately dry-runs the multichip path).

NOTE: on this image the ``JAX_PLATFORMS`` env var is IGNORED — the axon TPU
plugin wins platform selection regardless. ``jax.config.update`` before the
backend initializes is what actually works; ``XLA_FLAGS`` only needs to be
set before the first backend-initializing jax call.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
