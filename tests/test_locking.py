"""Runtime lock-hierarchy sentinels (``core/locking.py``).

The declared tier table (service > buffer > commit > shard > ring) is
the runtime half of the concurrency plane: in debug mode every
acquisition asserts monotone tier descent per thread and counts
contention. These tests pin the enforcement semantics — including the
regression the plane exists for: re-introducing the PR-4 merge-wedge
shape (commit-cond work under a shard leaf lock) must be DETECTED, not
silently deadlock-prone.
"""

import threading

import pytest

from d4pg_tpu.core import locking
from d4pg_tpu.core.locking import (
    HIERARCHY, LockHierarchyError, TieredCondition, TieredLock,
)

pytestmark = pytest.mark.concurrency


@pytest.fixture
def debug_mode():
    locking.enable_debug(raise_on_violation=True)
    locking.reset_stats()
    yield
    locking.disable_debug()
    locking.reset_stats()


def test_hierarchy_table_shape():
    # outermost first, strictly decreasing: the elastic control plane
    # above everything (the autoscaler may never be climbed INTO from a
    # data-plane lock), the five ingest-plane tiers, the multi-learner
    # pair (replica > aggregator), the weight plane's three (relay >
    # server cache > store), and the serving plane's condition wedged
    # between the weight server and the store
    assert list(HIERARCHY) == ["elastic", "service", "buffer", "replica",
                               "agg", "commit", "wrelay", "wserve",
                               "pserve", "wstore", "shard", "sampler",
                               "ring"]
    tiers = list(HIERARCHY.values())
    assert tiers == sorted(tiers, reverse=True)
    assert len(set(tiers)) == len(tiers)


def test_static_pass_mirrors_runtime_hierarchy():
    """The lint package is stdlib-only (no jax via d4pg_tpu.core), so
    lockgraph MIRRORS the tier table instead of importing it; this pin
    is what keeps the two declarations one source of truth."""
    from d4pg_tpu.lint.lockgraph import _TIER_VALUES

    assert _TIER_VALUES == HIERARCHY


def test_descent_is_legal_and_tracked(debug_mode):
    svc, buf, ring = (TieredLock("service"), TieredLock("buffer"),
                      TieredLock("ring"))
    with svc:
        with buf:
            with ring:
                assert [n for _, n in locking.held_tiers()] == [
                    "service", "buffer", "ring"]
    assert locking.held_tiers() == []
    assert locking.violation_count() == 0


def test_sequential_same_tier_is_legal(debug_mode):
    a, b = TieredCondition("shard"), TieredCondition("shard")
    with a:
        pass
    with b:
        pass
    assert locking.violation_count() == 0


def test_inverted_acquisition_raises(debug_mode):
    """The unit acceptance bar: a deliberately inverted acquisition
    (buffer while holding ring — ascent) raises immediately."""
    buf, ring = TieredLock("buffer"), TieredLock("ring")
    with ring:
        with pytest.raises(LockHierarchyError, match="hierarchy violation"):
            buf.acquire()
    assert locking.violation_count() == 1


def test_equal_tier_nesting_raises(debug_mode):
    # two sibling shard conditions held at once: the hidden worker-vs-
    # worker deadlock; strict descent forbids equal tiers too
    a, b = TieredCondition("shard"), TieredCondition("shard")
    with a:
        with pytest.raises(LockHierarchyError):
            b.acquire()


def test_merge_wedge_shape_is_caught(debug_mode):
    """THE regression: revert the PR-4 discipline locally — do
    commit-cond work while holding a shard leaf condition (the shape
    whose cross-thread interleaving wedged the ordered merge) — on the
    REAL service's locks, and assert the runtime sentinel catches it."""
    from d4pg_tpu.distributed.replay_service import ReplayService
    from d4pg_tpu.replay.uniform import ReplayBuffer

    svc = ReplayService(ReplayBuffer(128, 3, 2), num_ingest_shards=2)
    try:
        shard = svc._shards[0]
        with pytest.raises(LockHierarchyError):
            with shard.cond:           # leaf held ...
                with svc._commit_cond:  # ... merge work under it: WEDGE
                    pass
        # ... and the old review bug: settling service accounting
        # (_pending, under _lock) INSIDE the merge condition
        with pytest.raises(LockHierarchyError):
            with svc._commit_cond:
                with svc._lock:
                    pass
        # the shipped discipline itself stays silent: commit-cond then
        # (sequentially) the service lock, exactly as _commit_loop runs
        with svc._commit_cond:
            pass
        with svc._lock:
            pass
    finally:
        locking.disable_debug()  # close() joins threads that wait()
        svc.close()


def test_record_mode_counts_instead_of_raising():
    locking.enable_debug(raise_on_violation=False)
    locking.reset_stats()
    try:
        svc, ring = TieredLock("service"), TieredLock("ring")
        with ring:
            with svc:  # ascent — recorded, not raised
                pass
        assert locking.violation_count() == 1
        assert "hierarchy violation" in locking.hierarchy_violations()[0]
    finally:
        locking.disable_debug()
        locking.reset_stats()


def test_condition_wait_keeps_stack_consistent(debug_mode):
    cond = TieredCondition("commit")
    done = []

    def waiter():
        with cond:
            cond.wait(timeout=0.05)
            done.append(locking.held_tiers())

    t = threading.Thread(target=waiter)
    t.start()
    t.join(timeout=5.0)
    assert done and [n for _, n in done[0]] == ["commit"]
    assert locking.held_tiers() == []  # main thread untouched
    stats = locking.lock_stats()
    assert stats["commit"]["cond_waits"] == 1


def test_contention_counters(debug_mode):
    lock = TieredLock("service")
    lock.acquire()
    seen = []

    def contender():
        with lock:
            seen.append(True)

    t = threading.Thread(target=contender)
    t.start()
    # let the contender hit the held lock, then release
    for _ in range(1000):
        if lock._contended:
            break
        threading.Event().wait(0.001)
    lock.release()
    t.join(timeout=5.0)
    assert seen
    stats = locking.lock_stats()["service"]
    assert stats["acquisitions"] == 2
    assert stats["contended"] == 1
    assert stats["wait_ns"] > 0
    assert stats["max_hold_ns"] > 0


def test_production_mode_is_plain_delegation():
    assert not locking.debug_enabled()
    lock, cond = TieredLock("buffer"), TieredCondition("shard")
    with lock:
        pass
    with cond:
        cond.notify_all()
    # no bookkeeping happened
    assert locking.held_tiers() == []
    assert lock._acquisitions == 0


def test_unknown_tier_rejected():
    with pytest.raises(ValueError):
        TieredLock("no-such-tier")
    custom = TieredLock("custom", tier=99)  # explicit tier escape hatch
    with custom:
        pass
