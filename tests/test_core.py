"""Losses, target updates, noise, MoG math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.core import (
    CategoricalSupport,
    categorical_td_loss,
    expected_q,
    gaussian,
    hard_update,
    ou,
    policy_loss,
    soft_update,
)
from d4pg_tpu.core.losses import cross_entropy_per_sample, reference_td_error
from d4pg_tpu.core.mog import mog_log_prob, mog_mean, mog_target, mog_td_loss
from d4pg_tpu.models.critic import MoGParams


def test_categorical_td_loss_matches_reference_formula(rng):
    proj = rng.random((8, 51)).astype(np.float32)
    proj /= proj.sum(-1, keepdims=True)
    q = rng.random((8, 51)).astype(np.float32)
    q /= q.sum(-1, keepdims=True)
    loss, td = categorical_td_loss(jnp.asarray(proj), jnp.asarray(q))
    want = -(proj * np.log(q + 1e-10)).sum(-1)  # ddpg.py:217
    np.testing.assert_allclose(np.asarray(td), want, rtol=1e-5)
    assert loss == pytest.approx(want.mean(), rel=1e-5)
    # IS weights reweight the mean
    w = rng.random(8).astype(np.float32)
    loss_w, _ = categorical_td_loss(jnp.asarray(proj), jnp.asarray(q), jnp.asarray(w))
    assert loss_w == pytest.approx((w * want).mean(), rel=1e-5)


def test_reference_td_error_formula(rng):
    proj = rng.random((4, 11)).astype(np.float32)
    q = rng.random((4, 11)).astype(np.float32)
    got = np.asarray(reference_td_error(jnp.asarray(proj), jnp.asarray(q)))
    np.testing.assert_allclose(got, -(proj * q).sum(-1), rtol=1e-5)


def test_policy_loss_is_negative_expected_q():
    support = CategoricalSupport(-1.0, 1.0, 3)
    probs = jnp.array([[0.0, 0.0, 1.0], [1.0, 0.0, 0.0]])
    assert np.asarray(expected_q(support, probs)) == pytest.approx([1.0, -1.0])
    assert policy_loss(support, probs) == pytest.approx(0.0)


def test_soft_update_lerp():
    t = {"w": jnp.ones(3)}
    o = {"w": jnp.zeros(3)}
    out = soft_update(t, o, tau=0.1)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.9)
    h = hard_update(t, o)
    np.testing.assert_allclose(np.asarray(h["w"]), 0.0)


def test_gaussian_noise_decay_and_scale():
    st = gaussian.init(horizon=100)
    assert float(st.epsilon) == pytest.approx(0.3)
    key = jax.random.PRNGKey(0)
    s = gaussian.sample(st, key, (4,))
    # epsilon-scaled: same key with eps=1 gives s / 0.3
    st1 = st._replace(epsilon=jnp.asarray(1.0))
    np.testing.assert_allclose(
        np.asarray(gaussian.sample(st1, key, (4,))) * 0.3, np.asarray(s), rtol=1e-6
    )
    # reset decays epsilon monotonically toward min
    eps = [float(st.epsilon)]
    for _ in range(200):
        st = gaussian.reset(st, horizon=100)
        eps.append(float(st.epsilon))
    assert eps[-1] == pytest.approx(0.01, abs=1e-3)
    assert all(b <= a or a == pytest.approx(0.3) for a, b in zip(eps[1:], eps[2:]))


def test_ou_noise_mean_reversion_and_reset():
    st = ou.init(act_dim=2)
    key = jax.random.PRNGKey(1)
    xs = []
    for i in range(500):
        st, x = ou.sample(st, jax.random.fold_in(key, i), theta=0.5, sigma=0.05)
        xs.append(np.asarray(x))
    xs = np.stack(xs)
    assert np.abs(xs.mean(0)).max() < 0.5  # mean-reverts around 0
    st = ou.reset(st, horizon=100)
    np.testing.assert_allclose(np.asarray(st.x), 0.0)
    assert float(st.epsilon) < 1.0


def test_mog_target_and_loss_decreases_toward_truth():
    params = MoGParams(
        log_weights=jnp.log(jnp.array([[0.5, 0.5]])),
        means=jnp.array([[0.0, 2.0]]),
        stds=jnp.array([[1.0, 1.0]]),
    )
    # Bellman map
    tgt = mog_target(params, rewards=jnp.array([1.0]), discounts=jnp.array([0.5]))
    np.testing.assert_allclose(np.asarray(tgt.means), [[1.0, 2.0]])
    np.testing.assert_allclose(np.asarray(tgt.stds), [[0.5, 0.5]])
    assert mog_mean(params) == pytest.approx(1.0)
    # terminal collapse: discount 0 -> point-ish mass at r (std floored)
    term = mog_target(params, jnp.array([3.0]), jnp.array([0.0]))
    np.testing.assert_allclose(np.asarray(term.means), [[3.0, 3.0]])
    # CE(target, pred) is lower when pred == target than when far away
    key = jax.random.PRNGKey(0)
    loss_match, td = mog_td_loss(tgt, tgt, key, n_samples=256)
    far = MoGParams(tgt.log_weights, tgt.means + 10.0, tgt.stds)
    loss_far, _ = mog_td_loss(far, tgt, key, n_samples=256)
    assert float(loss_match) < float(loss_far)
    assert td.shape == (1,)


def test_mog_log_prob_matches_scipy_single_gaussian():
    from scipy.stats import norm

    params = MoGParams(
        log_weights=jnp.zeros((1, 1)), means=jnp.array([[1.5]]), stds=jnp.array([[2.0]])
    )
    x = jnp.array([[0.0, 1.5, 4.0]])
    got = np.asarray(mog_log_prob(params, x))[0]
    want = norm.logpdf([0.0, 1.5, 4.0], loc=1.5, scale=2.0)
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_cross_entropy_nonnegative_vs_entropy(rng):
    p = rng.random((16, 21)).astype(np.float32)
    p /= p.sum(-1, keepdims=True)
    ce = np.asarray(cross_entropy_per_sample(jnp.asarray(p), jnp.asarray(p)))
    ent = -(p * np.log(p + 1e-10)).sum(-1)
    np.testing.assert_allclose(ce, ent, rtol=1e-5)
