"""Distributed-runtime tests: weight store, replay service (ingest,
heartbeats, backpressure), actor workers incl. the HER goal actor, the
evaluator's EWMA, and the socket transport — all on fake envs, no MuJoCo
(SURVEY.md §4)."""

import time

import jax
import numpy as np
import pytest

from d4pg_tpu.distributed import (
    ActorConfig,
    ActorWorker,
    Evaluator,
    ReplayService,
    TransitionReceiver,
    TransitionSender,
    WeightStore,
)
from d4pg_tpu.distributed.actor import GoalActorWorker, _BaseActor
from d4pg_tpu.envs import EnvPool, FakeGoalEnv, PointMassEnv
from d4pg_tpu.learner import D4PGConfig, init_state
from d4pg_tpu.replay import PrioritizedReplayBuffer, ReplayBuffer
from d4pg_tpu.replay.uniform import TransitionBatch


def _batch(n=8, obs_dim=4, act_dim=2):
    rng = np.random.default_rng(0)
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.standard_normal((n, act_dim)).astype(np.float32),
        reward=np.ones(n, np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )


def test_weight_store_versions():
    ws = WeightStore()
    assert ws.get() == (0, None)
    v1 = ws.publish({"w": np.ones(3)}, step=10)
    assert v1 == 1 and ws.step == 10
    assert ws.get_if_newer(0)[0] == 1
    assert ws.get_if_newer(1) is None


def test_replay_service_ingest_and_counts():
    svc = ReplayService(ReplayBuffer(100, 4, 2))
    svc.add(_batch(8), actor_id="a0")
    svc.add(_batch(8), actor_id="a1")
    svc.flush()
    assert len(svc) == 16
    assert svc.env_steps == 16
    batch = svc.sample(4)
    assert batch.obs.shape == (4, 4)
    assert svc.dead_actors() == []
    svc.close()


def test_replay_service_per_dispatch():
    svc = ReplayService(PrioritizedReplayBuffer(100, 4, 2))
    svc.add(_batch(8))
    svc.flush()
    batch, w, idx, gen = svc.sample(4, beta=0.5)
    assert w.shape == (4,) and idx.shape == (4,) and gen.shape == (4,)
    svc.update_priorities(idx, np.full(4, 2.0), generation=gen)
    svc.close()


def test_replay_service_heartbeat_timeout():
    svc = ReplayService(ReplayBuffer(10, 4, 2), heartbeat_timeout=0.05)
    svc.heartbeat("a0")
    time.sleep(0.1)
    assert svc.dead_actors() == ["a0"]
    svc.heartbeat("a0")
    assert svc.dead_actors() == []
    svc.close()


def test_actor_worker_streams_transitions():
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(10_000, 4, 2))
    ws = WeightStore()
    state = init_state(config, jax.random.key(0))
    ws.publish(state.actor_params, step=0)
    pool = EnvPool([lambda s=i: PointMassEnv(horizon=20, seed=s) for i in range(4)])
    actor = ActorWorker("a0", config, ActorConfig(n_step=3, gamma=0.99),
                        pool, svc, ws, seed=1)
    steps = actor.run(max_steps=40)
    svc.flush()
    assert steps == 160  # 40 ticks x 4 envs
    assert len(svc) > 100  # n-step folding emits slightly fewer than steps
    assert svc.env_steps == len(svc)
    # epsilon decayed across episode boundaries (2 boundaries per env)
    assert actor._epsilon < ActorConfig().epsilon_0
    svc.close()


def test_actor_run_resumes_across_cycles():
    """Two run() calls must continue the same episodes (no pool re-reset, no
    stale n-step window stitched across the boundary): transition count and
    episode accounting match one combined run."""
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    ws = WeightStore()
    ws.publish(init_state(config, jax.random.key(0)).actor_params, step=0)

    def collect(tick_chunks):
        svc = ReplayService(ReplayBuffer(10_000, 4, 2))
        pool = EnvPool([lambda s=i: PointMassEnv(horizon=20, seed=s)
                        for i in range(2)], seed=0)
        actor = ActorWorker("a", config, ActorConfig(n_step=3), pool, svc, ws,
                            seed=5)
        for ticks in tick_chunks:
            actor.run(ticks)
        svc.flush()
        n, eps = len(svc), len(pool.episode_returns)
        svc.close()
        return n, eps

    assert collect([10, 10]) == collect([20])


def test_actor_ou_noise_path():
    """noise='ou' runs the temporally-correlated process (the reference's
    dead --ou_* flags, wired for real) and resets it at episode boundaries."""
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(10_000, 4, 2))
    ws = WeightStore()
    ws.publish(init_state(config, jax.random.key(0)).actor_params, step=0)
    pool = EnvPool([lambda: PointMassEnv(horizon=10, seed=0)])
    actor = ActorWorker("ou0", config, ActorConfig(noise="ou", ou_sigma=0.3),
                        pool, svc, ws, seed=3)
    actor.run(max_steps=10)  # crosses one episode boundary (horizon 10)
    svc.flush()
    assert len(svc) > 0
    assert actor._ou is not None
    # episode ended on the last tick -> OU state was zeroed
    np.testing.assert_allclose(np.asarray(actor._ou.x), 0.0, atol=1e-7)
    svc.close()


def test_actor_without_weights_uses_random_policy():
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(1000, 4, 2))
    ws = WeightStore()  # never published
    pool = EnvPool([lambda: PointMassEnv(horizon=20, seed=0)])
    actor = ActorWorker("a0", config, ActorConfig(), pool, svc, ws)
    actor.run(max_steps=10)
    svc.flush()
    assert len(svc) > 0
    svc.close()


def test_goal_actor_her_streams_relabels():
    """Goal actor streams originals + HER relabels; relabeled fraction >0."""
    obs_dim = 2 + 2  # observation + goal
    config = D4PGConfig(obs_dim=obs_dim, act_dim=2, v_min=-50, v_max=0,
                        n_atoms=11, hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(10_000, obs_dim, 2))
    ws = WeightStore()
    env = FakeGoalEnv(horizon=30, seed=0)
    actor = GoalActorWorker("g0", config, ActorConfig(gamma=0.98), env, svc, ws,
                            her_ratio=1.0, rng_seed=2)
    T = actor.run_episode(max_steps=30)
    svc.flush()
    assert T > 0
    # originals + relabels: exactly 2T rows with her_ratio=1.0
    assert len(svc) == 2 * T
    svc.close()


def test_random_eps_exploration():
    """random_eps=1 replaces every policy action with a uniform one (the
    HER-recipe epsilon-greedy); 0 keeps pure policy+noise actions."""
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(1000, 4, 2))
    ws = WeightStore()
    import jax as _jax

    from d4pg_tpu.learner import init_state

    ws.publish(init_state(config, _jax.random.key(0)).actor_params, step=0)
    obs = np.zeros((64, 4), np.float32)

    def actions_with(eps):
        a = _BaseActor("a0", config, ActorConfig(random_eps=eps), svc, ws,
                       seed=5)
        a._maybe_pull_weights()
        return a._explore_actions(obs)

    pure = actions_with(0.0)
    mixed = actions_with(1.0)
    # identical obs rows -> identical policy actions up to noise draw;
    # uniform replacement must decorrelate them from the pure run
    assert not np.allclose(pure, mixed)
    assert np.all(np.abs(mixed) <= 1.0)
    svc.close()


def test_goal_actor_on_wrapped_env():
    """gymnasium 1.x wrappers do not forward attributes: compute_reward
    must be resolved through env.unwrapped (regression: FetchReach-v4
    under TimeLimit crashed the HER relabel with AttributeError)."""

    class NonForwardingWrapper:
        """Minimal gymnasium-1.x-style wrapper: exposes ONLY the core API
        plus .unwrapped — no attribute forwarding."""

        def __init__(self, env):
            self.unwrapped = env
            self.action_space = env.action_space
            self.observation_space = env.observation_space

        def reset(self, **kw):
            return self.unwrapped.reset(**kw)

        def step(self, a):
            return self.unwrapped.step(a)

    obs_dim = 2 + 2
    config = D4PGConfig(obs_dim=obs_dim, act_dim=2, v_min=-50, v_max=0,
                        n_atoms=11, hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(10_000, obs_dim, 2))
    ws = WeightStore()
    env = NonForwardingWrapper(FakeGoalEnv(horizon=30, seed=0))
    actor = GoalActorWorker("g0", config, ActorConfig(gamma=0.98), env, svc,
                            ws, her_ratio=1.0, rng_seed=2)
    T = actor.run_episode(max_steps=30)
    svc.flush()
    assert T > 0 and len(svc) == 2 * T
    svc.close()


def test_evaluator_ewma_and_success():
    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    ws = WeightStore()
    ev = Evaluator(config, lambda: PointMassEnv(horizon=10, seed=7), ws,
                   max_steps=10)
    with pytest.raises(RuntimeError):
        ev.evaluate(n_trials=1)
    state = init_state(config, jax.random.key(0))
    ws.publish(state.actor_params, step=5)
    m1 = ev.evaluate(n_trials=2, seed=0)
    assert m1["learner_step"] == 5
    assert m1["avg_test_reward"] == m1["ewma_test_reward"]  # first call seeds EWMA
    m2 = ev.evaluate(n_trials=2, seed=0)
    expected = 0.95 * m1["ewma_test_reward"] + 0.05 * m2["avg_test_reward"]
    np.testing.assert_allclose(m2["ewma_test_reward"], expected, rtol=1e-9)


def test_socket_transport_roundtrip():
    """Frames survive the wire; receiver feeds the service callback."""
    svc = ReplayService(ReplayBuffer(1000, 4, 2))
    recv = TransitionReceiver(lambda b, aid, count: svc.add(
        b, actor_id=aid, count_env_steps=count),
                              host="127.0.0.1")
    sender = TransitionSender("127.0.0.1", recv.port, actor_id="remote-7")
    sent = _batch(16)
    sender.send(sent)
    sender.send(_batch(16))
    deadline = time.monotonic() + 5.0
    while len(svc) < 32 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(svc) == 32
    got = svc.buffer.gather(np.arange(16))
    np.testing.assert_allclose(got.obs, sent.obs, atol=0)
    np.testing.assert_allclose(got.discount, sent.discount, atol=0)
    sender.close()
    recv.close()
    svc.close()


class _ScaledSpyGoalEnv(FakeGoalEnv):
    """FakeGoalEnv with a non-(-1,1) action box that records what it is
    stepped with. Regression guard for VERDICT r1 #4: the round-1 goal actor
    stepped raw tanh actions while the Evaluator rescaled."""

    def __init__(self, scale: float, **kw):
        super().__init__(**kw)
        from d4pg_tpu.envs.fake import _Box

        self.scale = scale
        self.action_space = _Box(-scale, scale, (2,))
        self.stepped_actions: list[np.ndarray] = []

    def step(self, action):
        self.stepped_actions.append(np.asarray(action, np.float32).copy())
        return super().step(np.asarray(action, np.float32) / self.scale)


def test_goal_actor_rescales_actions():
    from d4pg_tpu.envs.wrappers import rescale_action

    obs_dim = 2 + 2
    config = D4PGConfig(obs_dim=obs_dim, act_dim=2, v_min=-50, v_max=0,
                        n_atoms=11, hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(10_000, obs_dim, 2))
    ws = WeightStore()
    env = _ScaledSpyGoalEnv(scale=5.0, horizon=20, seed=3)
    actor = GoalActorWorker("g0", config, ActorConfig(), env, svc, ws,
                            her_ratio=0.0, rng_seed=4, seed=4)
    T = actor.run_episode(max_steps=20)
    svc.flush()
    stepped = np.stack(env.stepped_actions)
    stored = svc.buffer.gather(np.arange(T)).action
    # env sees the affine-rescaled action, buffer keeps the tanh-space one
    low = np.full(2, -5.0, np.float32)
    high = np.full(2, 5.0, np.float32)
    np.testing.assert_allclose(stepped, rescale_action(stored, low, high),
                               rtol=1e-6, atol=1e-6)
    assert np.abs(stepped).max() > 1.0  # actually left the tanh range
    assert np.abs(stored).max() <= 1.0
    svc.close()


def test_async_evaluator_runs_off_thread():
    from d4pg_tpu.distributed import AsyncEvaluator

    config = D4PGConfig(obs_dim=4, act_dim=2, v_min=-5, v_max=0, n_atoms=11,
                        hidden=(16, 16))
    ws = WeightStore()
    ev = Evaluator(config, lambda: PointMassEnv(horizon=10, seed=7), ws,
                   max_steps=10)
    state = init_state(config, jax.random.key(0))
    ws.publish(state.actor_params, step=3)
    aev = AsyncEvaluator(ev)
    assert aev.latest() is None
    assert aev.request(n_trials=2, seed=0)
    got = aev.wait(timeout=60.0)
    assert got is not None and got["learner_step"] == 3
    assert np.isfinite(got["avg_test_reward"])
    # latest() returns a copy, not a live reference
    got["avg_test_reward"] = 1e9
    assert aev.latest()["avg_test_reward"] != 1e9
    aev.close()


def test_her_relabels_do_not_inflate_env_steps():
    """env_steps counts fresh interaction only; HER relabels are synthetic
    (ADVICE r1: drain counted both, inflating by (1+her_ratio)x)."""
    obs_dim = 2 + 2
    config = D4PGConfig(obs_dim=obs_dim, act_dim=2, v_min=-50, v_max=0,
                        n_atoms=11, hidden=(16, 16))
    svc = ReplayService(ReplayBuffer(10_000, obs_dim, 2))
    ws = WeightStore()
    env = FakeGoalEnv(horizon=30, seed=0)
    actor = GoalActorWorker("g0", config, ActorConfig(gamma=0.98), env, svc, ws,
                            her_ratio=1.0, rng_seed=2)
    T = actor.run_episode(max_steps=30)
    svc.flush()
    assert len(svc) == 2 * T  # both row kinds stored...
    assert svc.env_steps == T  # ...but only real steps counted
    svc.close()


def test_transport_rejects_wrong_secret_and_oversized_frames():
    import socket
    import struct
    import time as _time

    svc = ReplayService(ReplayBuffer(1000, 4, 2))
    recv = TransitionReceiver(lambda b, aid, count: svc.add(
        b, actor_id=aid, count_env_steps=count),
                              host="127.0.0.1", secret="sesame",
                              max_payload=1 << 20)
    # right secret: frames land
    good = TransitionSender("127.0.0.1", recv.port, actor_id="ok",
                            secret="sesame")
    good.send(_batch(4))
    deadline = _time.monotonic() + 5
    while len(svc) < 4 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert len(svc) == 4
    good.close()
    # wrong secret: the server drops the connection before reading frames
    bad = TransitionSender("127.0.0.1", recv.port, actor_id="evil",
                           secret="wrong")
    try:
        for _ in range(50):
            bad.send(_batch(4))
            _time.sleep(0.005)
    except OSError:
        pass  # broken pipe once the server hangs up
    finally:
        bad.close()
    assert len(svc) == 4  # nothing new landed
    # authenticated peer claiming an absurd frame length is dropped too
    sock = socket.create_connection(("127.0.0.1", recv.port))
    from d4pg_tpu.distributed.transport import client_handshake
    client_handshake(sock, "sesame")
    sock.sendall(struct.pack("!II", 0xD4F6, 1 << 30))
    _time.sleep(0.2)
    sock.close()
    assert len(svc) == 4
    recv.close()
    svc.close()


def test_weight_plane_secret():
    from d4pg_tpu.distributed.weight_server import WeightClient, WeightServer

    ws = WeightStore()
    ws.publish({"w": np.arange(4.0)}, step=1)
    server = WeightServer(ws, host="127.0.0.1", secret="sesame")
    client = WeightClient("127.0.0.1", server.port, secret="sesame")
    version, params = client.get_if_newer(0)
    assert version == 1
    np.testing.assert_array_equal(params["w"], np.arange(4.0))
    client.close()
    bad = WeightClient("127.0.0.1", server.port, secret="nope")
    with pytest.raises(ConnectionError):
        bad.get_if_newer(0)
    bad.close()
    server.close()
