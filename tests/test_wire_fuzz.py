"""Hostile-frame fuzz across all five wire planes (marker ``wire``).

Every decoder that faces a socket gets seeded torn/truncated/bit-flipped
frames and must uphold the same three-part contract the wire lint
(``python -m d4pg_tpu.lint --wire``) enforces statically:

  1. no serving thread dies (a hostile peer cannot crash the plane),
  2. every rejection is COUNTED (``frames_rejected`` / ``torn`` /
     ``torn_rejected``), never silent,
  3. traced frames that are rejected shed their span — 0 orphans.

Plus the satellite pin: the registry-declared ``ingest_v2_layout``
offsets that ``raw_frame_meta_ex`` reads must agree bytewise with the
full ``decode_raw`` across every flag combination.
"""

import io
import socket
import threading
import time
import zlib

import numpy as np
import pytest

from d4pg_tpu.core import wire
from d4pg_tpu.distributed.transport import (
    ProtocolError,
    TransitionReceiver,
    TransitionSender,
    _recv_exact,
    decode_raw,
    encode_raw,
    raw_frame_meta_ex,
)
from d4pg_tpu.replay.uniform import TransitionBatch

pytestmark = pytest.mark.wire


def _batch(n=4, obs_dim=3, act_dim=2, seed=0):
    rng = np.random.default_rng(seed)
    return TransitionBatch(
        obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        action=rng.standard_normal((n, act_dim)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, obs_dim)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.ones(n, np.float32),
    )


class _CrashTrap:
    """Capture unhandled thread exceptions: a dead serve thread is a
    test failure even when the socket side looks fine."""

    def __enter__(self):
        self.crashes = []
        self._orig = threading.excepthook
        threading.excepthook = lambda a: self.crashes.append(a)
        return self

    def __exit__(self, *exc):
        threading.excepthook = self._orig
        return False


def _fake_server(handler):
    """One-connection TCP server running ``handler(conn)`` on a thread."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen()

    def run():
        try:
            conn, _ = srv.accept()
        except OSError:
            return
        with conn:
            try:
                handler(conn)
            except OSError:
                pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return srv, srv.getsockname()[1], t


# ------------------------------------------------------ ingest plane ----

def test_ingest_receiver_counts_hostile_frames_and_survives():
    """Bad magic, oversize length, and a hostile-but-well-framed body
    are each a COUNTED rejection; a truncated frame (peer death) is a
    clean uncounted drop; the receiver keeps serving afterwards."""
    from d4pg_tpu.obs.registry import REGISTRY

    crashes0 = REGISTRY.counter("threads.contained_crashes").value
    with _CrashTrap() as trap:
        received = []
        recv = TransitionReceiver(lambda b, aid, c: received.append(b),
                                  host="127.0.0.1")
        try:
            hostile = [
                # wrong magic, plausible length
                wire.FRAME_HEADER.pack(0xDEAD, 16) + b"\x00" * 16,
                # declared magic, oversize length
                wire.FRAME_HEADER.pack(wire.MAGIC_INGEST_V2,
                                       wire.MAX_PAYLOAD + 1),
                # well-framed v2 body that detonates inside decode_raw
                # (flags=0xFF, aid_len=0xFF -> UnicodeDecodeError)
                wire.FRAME_HEADER.pack(wire.MAGIC_INGEST_V2, 64)
                + b"\xff" * 64,
            ]
            for frame in hostile:
                c = socket.create_connection(("127.0.0.1", recv.port))
                c.sendall(frame)
                c.settimeout(5.0)
                try:
                    assert c.recv(1) == b""  # graceful drop (FIN)
                except ConnectionResetError:
                    pass  # abortive drop (RST on unread bytes): same verdict
                c.close()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and recv.frames_rejected < 3:
                time.sleep(0.02)
            assert recv.frames_rejected == 3

            # truncated mid-frame: peer death, dropped but NOT counted
            c = socket.create_connection(("127.0.0.1", recv.port))
            c.sendall(wire.FRAME_HEADER.pack(wire.MAGIC_INGEST_V2, 100)
                      + b"\x00" * 10)
            c.close()

            # seeded bit-flip storm over a valid frame: whatever the
            # mutation does, no serve thread may die
            rng = np.random.default_rng(1337)
            good = encode_raw("actor-0", _batch())
            for _ in range(16):
                mut = bytearray(good)
                for _ in range(int(rng.integers(1, 6))):
                    mut[int(rng.integers(wire.FRAME_HEADER.size,
                                         len(mut)))] ^= 1 << int(
                        rng.integers(8))
                c = socket.create_connection(("127.0.0.1", recv.port))
                c.sendall(bytes(mut))
                c.close()

            # the plane still serves a fresh, honest sender
            sender = TransitionSender("127.0.0.1", recv.port,
                                      actor_id="ok")
            assert sender.send(_batch()) is True
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not received:
                time.sleep(0.02)
            assert received
            sender.close()
        finally:
            recv.close()
    assert not trap.crashes, trap.crashes
    # hostile frames ride the narrow protocol-error paths; the broad
    # top-frame containment (which would hide a crash from the trap
    # above) must not have fired either
    assert REGISTRY.counter("threads.contained_crashes").value == crashes0


# ------------------------------------------------- weights v1 plane ----

def test_weights_v1_server_drops_garbage_request_then_serves():
    from d4pg_tpu.distributed.weight_server import WeightClient, WeightServer
    from d4pg_tpu.distributed.weights import WeightStore

    with _CrashTrap() as trap:
        store = WeightStore()
        store.publish({"w": np.ones((2, 2), np.float32)}, step=1,
                      to_host=False)
        srv = WeightServer(store, host="127.0.0.1")
        try:
            c = socket.create_connection(("127.0.0.1", srv.port))
            c.sendall(b"\xde\xad\xbe\xef" + b"\x00" * 8)  # bad magic req
            c.settimeout(5.0)
            assert c.recv(1) == b""  # dropped
            c.close()
            client = WeightClient("127.0.0.1", srv.port)
            got = client.get_if_newer(0)
            assert got is not None and got[0] == 1
            client.close()
        finally:
            srv.close()
    assert not trap.crashes, trap.crashes


def test_weights_v1_client_rejects_garbage_npz_as_protocol_error():
    """A well-framed response whose body is not an npz must surface as
    ProtocolError with the socket dropped — never an uncontained
    ValueError/BadZipFile through the acting thread."""
    from d4pg_tpu.distributed.weight_server import WeightClient

    def handler(conn):
        if _recv_exact(conn, wire.WEIGHTS_V1_REQ.size) is None:
            return
        garbage = b"\x9f" * 64
        conn.sendall(wire.WEIGHTS_V1_RESP.pack(
            wire.MAGIC_WEIGHTS_V1, len(garbage)) + garbage)
        time.sleep(0.5)

    srv, port, _t = _fake_server(handler)
    try:
        client = WeightClient("127.0.0.1", port, connect_timeout=5.0)
        with pytest.raises(ProtocolError):
            client.get_if_newer(0)
        assert client._sock is None  # socket dropped, not left desynced
        client.close()
    finally:
        srv.close()


# ------------------------------------------------- weights v2 plane ----

def test_weights_v2_crc_valid_garbage_counted_torn_not_crash():
    """crc32 passes (the sender checksummed garbage) but the body is not
    an npz: counted as torn_rejected, get_if_newer degrades to None."""
    from d4pg_tpu.distributed.weight_plane import WeightPlaneClient

    def handler(conn):
        if _recv_exact(conn, wire.WEIGHTS_V2_REQ.size) is None:
            return
        garbage = b"\x9f" * 64
        conn.sendall(wire.WEIGHTS_V2_RESP.pack(
            wire.MAGIC_WEIGHTS_V2, 1, zlib.crc32(garbage), len(garbage))
            + garbage)
        time.sleep(0.5)

    srv, port, _t = _fake_server(handler)
    try:
        client = WeightPlaneClient("127.0.0.1", port, connect_timeout=5.0)
        assert client.get_if_newer() is None  # stale degradation
        assert client.counters["torn_rejected"] == 1
        assert client.counters["accepts"] == 0
        client.close()
    finally:
        srv.close()


def test_weights_v2_torn_crc_counted(tmp_path):
    """The existing crc tear (body does not match header crc) stays a
    counted rejection on the same code path the fuzz exercises."""
    from d4pg_tpu.distributed.weight_plane import WeightPlaneClient

    def handler(conn):
        if _recv_exact(conn, wire.WEIGHTS_V2_REQ.size) is None:
            return
        garbage = b"\x9f" * 64
        conn.sendall(wire.WEIGHTS_V2_RESP.pack(
            wire.MAGIC_WEIGHTS_V2, 1, zlib.crc32(garbage) ^ 0xFFFF,
            len(garbage)) + garbage)
        time.sleep(0.5)

    srv, port, _t = _fake_server(handler)
    try:
        client = WeightPlaneClient("127.0.0.1", port, connect_timeout=5.0)
        assert client.get_if_newer() is None
        assert client.counters["torn_rejected"] == 1
        client.close()
    finally:
        srv.close()


# ---------------------------------------------------- update plane ----

def test_update_server_torn_garbage_acked_counted_conn_alive():
    """A crc-VALID update frame whose payload is not an npz must come
    back as a counted torn ack on a connection that stays usable, with
    the frame's trace span shed (0 orphans)."""
    from d4pg_tpu.distributed.update_plane import (
        AggregatorServer, STATUS_TORN, UpdateClient)
    from d4pg_tpu.distributed.weights import WeightStore
    from d4pg_tpu.learner.aggregator import Aggregator
    from d4pg_tpu.obs.trace import RECORDER as TRACE

    rng = np.random.default_rng(7)
    params = {"w": rng.standard_normal((3, 2)).astype(np.float32)}
    agg = Aggregator(WeightStore())
    server = AggregatorServer(agg)
    client = UpdateClient("127.0.0.1", server.port)
    TRACE.enable(sample_rate=1.0)
    try:
        epoch = agg.register(0, params=params)
        payload = b"\x13" * 48  # not an npz; crc below is VALID
        tid = 0xF00D
        frame = wire.UPDATE_HEADER.pack(
            wire.MAGIC_UPDATE, 0, epoch, 0, 0, 0, tid, time.time(), 0,
            zlib.crc32(payload), len(payload)) + payload
        res = client.submit_frame(frame)
        assert res["status"] == "torn"
        assert server.stats()["torn"] == 1
        assert TRACE.orphans() == []  # torn frame shed its span
        # the SAME connection still applies an honest update
        res2 = client.submit(0, epoch, params, agg.basis(0)[0],
                             generation=agg._store.generation)
        assert res2["status"] == "applied"
        assert server.stats()["applied"] == 1
        assert STATUS_TORN == 2  # wire status id is part of the protocol
    finally:
        TRACE.disable()
        TRACE.reset()
        client.close()
        server.close()
        agg.close()


def test_update_server_hostile_header_drops_conn_without_thread_death():
    from d4pg_tpu.distributed.update_plane import AggregatorServer
    from d4pg_tpu.distributed.weights import WeightStore
    from d4pg_tpu.learner.aggregator import Aggregator
    from d4pg_tpu.obs.registry import REGISTRY

    crashes0 = REGISTRY.counter("threads.contained_crashes").value
    with _CrashTrap() as trap:
        agg = Aggregator(WeightStore())
        server = AggregatorServer(agg)
        try:
            rng = np.random.default_rng(99)
            for _ in range(8):
                c = socket.create_connection(("127.0.0.1", server.port))
                c.sendall(rng.bytes(wire.UPDATE_HEADER.size))
                c.settimeout(5.0)
                assert c.recv(1) == b""  # dropped, not wedged
                c.close()
        finally:
            server.close()
            agg.close()
    assert not trap.crashes, trap.crashes
    # same bar as the ingest fuzz: the broad containment (invisible to
    # the excepthook trap) must not have absorbed a crash either
    assert REGISTRY.counter("threads.contained_crashes").value == crashes0


# --------------------------------------------------- serving plane ----

def test_serving_codec_mutation_fuzz_raises_only_protocol_errors():
    """Seeded byte-flips and truncations over valid request/response
    bodies: every mutation either decodes or raises the serving plane's
    ProtocolError family — nothing else escapes to the caller."""
    from d4pg_tpu.serving import protocol

    rng = np.random.default_rng(0x5EED)
    obs = rng.standard_normal((4, 8)).astype(np.float32)
    req = protocol.encode_request(7, obs, trace=(99, 1.5))
    actions = rng.standard_normal((4, 2)).astype(np.float32)
    rsp = protocol.encode_response(7, protocol.STATUS_OK, 3, 11, actions)
    cases = [(req[protocol.HEADER.size:], protocol.decode_request),
             (rsp[protocol.HEADER.size:], protocol.decode_response)]
    torn = 0
    for body, decode in cases:
        for _ in range(200):
            mut = bytearray(body)
            for _ in range(int(rng.integers(1, 4))):
                mut[int(rng.integers(len(mut)))] ^= 1 << int(
                    rng.integers(8))
            if rng.random() < 0.3:
                mut = mut[:int(rng.integers(len(mut)))]
            try:
                decode(bytes(mut))
            except protocol.TornFrameError:
                torn += 1
            except protocol.ProtocolError:
                pass
    assert torn > 0  # the crc actually caught payload tears


def test_serving_outer_frame_bad_magic_is_protocol_error():
    from d4pg_tpu.serving import protocol

    def handler(conn):
        conn.sendall(wire.FRAME_HEADER.pack(0xBEEF, 4) + b"\x00" * 4)
        time.sleep(0.5)

    srv, port, _t = _fake_server(handler)
    try:
        sock = socket.create_connection(("127.0.0.1", port))
        with pytest.raises(protocol.ProtocolError):
            protocol.read_frame(sock, protocol.MAGIC_RESPONSE, _recv_exact)
        sock.close()
    finally:
        srv.close()


# -------------------------------------------------- recovery plane ----

def test_sidecar_bitflip_rejected(tmp_path):
    from d4pg_tpu.io.checkpoint import (
        SnapshotCorruptError, load_replay_sidecar, save_replay_sidecar)

    path = save_replay_sidecar(str(tmp_path), 0, step=5,
                               snap={"rows": [1, 2, 3]})
    blob = bytearray(open(path, "rb").read())
    blob[wire.SIDECAR_HEAD.size + 3] ^= 0x01  # one bit, payload region
    with open(path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(SnapshotCorruptError):
        load_replay_sidecar(str(tmp_path), 0)


# --------------------------------- registry layout pin (satellite 6) ----

@pytest.mark.parametrize("count", [True, False])
@pytest.mark.parametrize("trace", [None, (0xABCDEF, 123.25)])
@pytest.mark.parametrize("generation", [None, 42])
def test_header_only_meta_matches_full_decode(count, trace, generation):
    """``raw_frame_meta_ex`` reads the registry-declared offsets; across
    every flag combination it must agree with the full ``decode_raw`` —
    the equality pin that keeps the header-only readers honest."""
    batch = _batch(n=6, seed=3)
    frame = encode_raw("actor-xyz", batch, count, trace=trace,
                       generation=generation)
    payload = frame[wire.FRAME_HEADER.size:]
    aid, n, got_count, got_trace, got_gen = raw_frame_meta_ex(payload)
    full_aid, full_batch, full_count = decode_raw(payload)
    assert aid == full_aid == "actor-xyz"
    assert n == len(full_batch.obs) == 6
    assert got_count == full_count == count
    assert got_trace == trace
    assert got_gen == generation
    for a, b in zip(full_batch, batch):
        assert np.array_equal(a, b)


def test_ingest_v2_layout_matches_running_offsets():
    """The declared layout function IS the running-offset arithmetic the
    original parser hand-rolled — pinned for every flag combination."""
    for flags in range(8):
        for aid_len in (0, 1, 7, 255):
            layout = wire.ingest_v2_layout(flags, aid_len)
            off = wire.RAW_PRE.size
            assert layout["aid"] == off
            off += aid_len
            if flags & wire.F_TRACE:
                assert layout["trace"] == off
                off += wire.RAW_TRACE.size
            else:
                assert layout["trace"] == -1
            if flags & wire.F_GEN:
                assert layout["generation"] == off
                off += wire.RAW_GEN.size
            else:
                assert layout["generation"] == -1
            assert layout["fields"] == off
