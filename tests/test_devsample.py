"""Device-resident PER sampling (replay/device_per.py descent +
replay/device_sampler.DeviceSampleDealer + ops/sampler_descent.py).

The load-bearing oracle is the seeded-stream lockstep: the device dealer
and its float32 host twin (``SampleDealer(scheme='device')`` — numpy
float32 trees, device stratification, the SHARED compiled weight
transform) consume identical RNG streams, so same seed must give
bitwise-identical ``(idx, weights, beta, rows, gen)``. The twin is
pinned against the float64 legacy descent separately, on dyadic-rational
priorities where float32 arithmetic is exact.

Tie rule (documented in ``device_per.descend`` and pinned here): at
every node, ``mass >= left_subtree_sum`` descends RIGHT — a mass equal
to a cumulative prefix boundary selects the first leaf AFTER the
boundary, so a zero-priority run at a boundary is skipped, never
sampled. All three implementations (f64 host, f32 twin, device) share
it.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.device_sampler import DeviceSampleDealer
from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay
from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.sampler import SampleDealer, ShardSlicePerTrees
from d4pg_tpu.replay.schedule import SharedBetaSchedule
from d4pg_tpu.replay.segment_tree import SumTree
from d4pg_tpu.replay.staging import DealtBlockRing, DeviceDealtBlockRing
from d4pg_tpu.replay.uniform import TransitionBatch

pytestmark = pytest.mark.devsample

CAP, K, B, OD, AD = 128, 2, 8, 4, 2


def _mk_batch(rng, n):
    return TransitionBatch(
        rng.random((n, OD)).astype(np.float32),
        rng.random((n, AD)).astype(np.float32),
        rng.random(n).astype(np.float32),
        rng.random((n, OD)).astype(np.float32),
        (rng.random(n) < 0.1).astype(np.float32),
        np.full(n, 0.99, np.float32))


def _device_rig(seed=42, ring_cls=DealtBlockRing, **kw):
    buf = FusedDeviceReplay(CAP, OD, AD, alpha=0.6, gen_tracked=True,
                            block_rows=32)
    ring = ring_cls(4)
    dealer = DeviceSampleDealer(CAP, [ring], k=K, batch_size=B, alpha=0.6,
                                beta_schedule=SharedBetaSchedule(),
                                min_size=8, seed=seed, **kw)
    dealer.resync(buf)
    return buf, ring, dealer


def _twin_rig(seed=42):
    buf = PrioritizedReplayBuffer(CAP, OD, AD, alpha=0.6, seed=0)
    ring = DealtBlockRing(4)
    dealer = SampleDealer(CAP, [ring], n_shards=1, k=K, batch_size=B,
                          alpha=0.6, beta_schedule=SharedBetaSchedule(),
                          min_size=8, seed=seed, scheme="device")
    dealer.resync(buf)
    return buf, ring, dealer


# --------------------------------------------- the seeded-stream oracle


def test_device_dealer_bitwise_equals_host_twin(rng):
    """Same seed, same ingest stream, same write-backs => the device
    dealer's blocks are BITWISE the host twin's: idx, weights, gen,
    beta/step, and every gathered row. Zero tolerance — the contract is
    equality of the sample STREAM, not distributional closeness."""
    dbuf, _dring, dd = _device_rig()
    hbuf, _hring, hd = _twin_rig()
    dealt_total = 0
    for step in range(6):
        batch = _mk_batch(rng, 10)
        dealt_d = dd.ingest_and_deal([(dbuf.add(batch), None, None)], dbuf)
        dealt_h = hd.ingest_and_deal([(hbuf.add(batch), None, None)], hbuf)
        assert len(dealt_d) == len(dealt_h)
        for (_ri, bd), (_rh, bh) in zip(dealt_d, dealt_h):
            np.testing.assert_array_equal(np.asarray(bd.idx), bh.idx)
            np.testing.assert_array_equal(np.asarray(bd.weights),
                                          bh.weights)
            assert bd.beta == bh.beta and bd.step == bh.step
            np.testing.assert_array_equal(np.asarray(bd.gen), bh.gen)
            for da, ha in zip(bd.batches, bh.batches):
                np.testing.assert_array_equal(np.asarray(da), ha)
            # identical TD write-backs keep the trees in lockstep too
            td = np.random.default_rng(step).uniform(
                0.1, 2.0, bh.idx.shape)
            dd.queue_writeback(bd.idx, td, bd.gen)
            hd.queue_writeback(bh.idx, td, bh.gen)
            dealt_total += 1
        dd.publish(dealt_d)
        hd.publish(dealt_h)
    assert dealt_total >= 4  # the oracle actually exercised deals


def test_twin_trees_match_f64_legacy_on_dyadic_priorities(rng):
    """The float32 twin tree vs the float64 legacy tree, on
    dyadic-rational priorities (k/16, k < 2**10) where every f32 sum is
    exact: identical descents for dyadic masses across the whole total
    range. This pins the twin to the legacy math where exactness is
    possible — the f32-vs-f64 gap on arbitrary reals is a rounding
    fact, not a defect, and is why the ORACLE twin is f32."""
    t32 = ShardSlicePerTrees(CAP, 1, dtype=np.float32)
    t64 = ShardSlicePerTrees(CAP, 1)
    idx = np.arange(CAP)
    pri = rng.integers(1, 1024, size=CAP).astype(np.float64) / 16.0
    t32.set(idx, pri)
    t64.set(idx, pri)
    assert t32.total() == t64.total()
    mass = (rng.integers(0, int(t64.total() * 16), size=256)
            .astype(np.float64) / 16.0)
    np.testing.assert_array_equal(t32.find_prefixsum(mass),
                                  t64.find_prefixsum(mass))


# ------------------------------------- descent edge-case property pins


def _host_ref(values):
    s = SumTree(len(values))
    s.set(np.arange(len(values)), np.asarray(values, np.float64))
    return s


def test_descent_all_zero_priorities():
    """All-zero tree: every left_sum is 0, and the tie rule
    (mass >= left_sum -> RIGHT) walks to the LAST leaf at every level —
    device and host agree, and the caller's size clamp then maps it
    into the live region. No NaNs, no index out of range."""
    cap = 16
    host = _host_ref(np.zeros(cap))
    trees = dper.init(cap)
    mass = np.array([0.0, 0.5, 1.0], np.float32)
    got = np.asarray(dper.descend(trees.sum_tree, jnp.asarray(mass)))
    np.testing.assert_array_equal(got, host.find_prefixsum(mass))
    np.testing.assert_array_equal(got, [cap - 1] * 3)
    # the deal-path clamp keeps the all-zero draw inside the live rows
    clamped = np.asarray(dper.sample_from_uniforms(
        trees, jnp.zeros((3,)), jnp.int32(5)))
    assert clamped.max() <= 4


def test_descent_capacity_boundary_wraparound(rng):
    """A commit block that wraps the capacity boundary must land its
    priorities in the wrapped slots — leaf writes go through
    ``(start + row) % capacity``, and the descent then sees exactly the
    host reference tree built from the same wrapped assignment."""
    buf = FusedDeviceReplay(12, OD, AD, alpha=0.6, gen_tracked=True,
                            block_rows=8)
    filler = _mk_batch(rng, 8)
    slots = []
    for _ in range(2):  # 16 rows into 12 slots: the 2nd block wraps
        slots.append(buf.add(filler))
        buf.drain()
    assert slots[1][-1] < slots[1][0]  # genuinely wrapped
    p = float(buf.max_priority) ** 0.6
    host = np.zeros(dper.init(12).capacity)
    host[np.concatenate(slots) % 12] = np.float32(p)
    ref = _host_ref(host)
    mass = (rng.random(64) * ref.sum()).astype(np.float32)
    got = np.asarray(dper.descend(buf.trees.sum_tree, jnp.asarray(mass)))
    np.testing.assert_array_equal(got, ref.find_prefixsum(mass))
    # wrapped slots were double-written: their generation advanced twice
    gen = np.asarray(buf.gen)
    wrapped = slots[1][slots[1] < slots[1][0]]
    assert (gen[wrapped] == 2).all()
    assert int(buf.size) == 12


def test_descent_single_leaf_tree():
    """capacity=1 degenerates to a two-node tree: zero descent levels,
    every mass maps to leaf 0 — device and host agree."""
    host = _host_ref([3.0])
    trees = dper.set_leaves(dper.init(1), jnp.array([0]),
                            jnp.array([3.0], jnp.float32))
    mass = np.array([0.0, 1.5, 2.999], np.float32)
    got = np.asarray(dper.descend(trees.sum_tree, jnp.asarray(mass)))
    np.testing.assert_array_equal(got, host.find_prefixsum(mass))
    np.testing.assert_array_equal(got, [0, 0, 0])


def test_descent_tie_rule_on_duplicate_prefixes():
    """Duplicate cumulative prefixes (zero-priority runs): leaves
    [1, 0, 0, 1] have prefix sums [1, 1, 1, 2]. The documented tie rule
    (mass >= left_sum -> RIGHT) sends mass exactly 1.0 PAST the zero
    run to leaf 3 — a zero-priority leaf is never selected by a
    boundary mass. Device and the f64 host reference agree bitwise."""
    vals = [1.0, 0.0, 0.0, 1.0]
    host = _host_ref(vals)
    trees = dper.set_leaves(dper.init(4), jnp.arange(4),
                            jnp.asarray(vals, jnp.float32))
    mass = np.array([0.0, 0.5, 1.0, 1.5], np.float32)
    got = np.asarray(dper.descend(trees.sum_tree, jnp.asarray(mass)))
    np.testing.assert_array_equal(got, host.find_prefixsum(mass))
    np.testing.assert_array_equal(got, [0, 0, 3, 3])


# ------------------------------------------------ pallas kernel parity


def test_pallas_descent_bitwise_equals_scan(rng):
    """The Pallas one-hot-contraction descent vs the jnp gather descent:
    bitwise-identical indices (0*x=0 and x+0=x are exact in IEEE f32,
    so the contraction IS a gather). Random trees with zero runs, plus
    the all-zero tree, across capacities including non-tile-multiple
    query counts."""
    from d4pg_tpu.ops.sampler_descent import descend_pallas

    for cap in (8, 64, 256):
        vals = rng.random(cap).astype(np.float32)
        vals[rng.random(cap) < 0.5] = 0.0
        trees = dper.set_leaves(dper.init(cap), jnp.arange(cap),
                                jnp.asarray(vals))
        total = float(trees.sum_tree[1])
        mass = jnp.asarray((rng.random(300) * total).astype(np.float32))
        want = np.asarray(dper.descend(trees.sum_tree, mass))
        got = np.asarray(descend_pallas(trees.sum_tree, mass, True))
        np.testing.assert_array_equal(got, want)
    zero = dper.init(16)
    mass = jnp.zeros((5,), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(descend_pallas(zero.sum_tree, mass, True)),
        np.asarray(dper.descend(zero.sum_tree, mass)))


# ------------------------------------- write-back fencing, device tree


def test_generation_fenced_writeback_lands_in_device_tree(rng):
    """A live write-back must land ``td ** alpha`` (host-side pow, f32)
    in the DEVICE sum tree's leaf; a stale-generation write-back for a
    since-overwritten slot must be dropped and counted, leaving the
    leaf at its commit-time priority."""
    buf, _ring, dealer = _device_rig()
    dealer.ingest_and_deal([(buf.add(_mk_batch(rng, 16)), None, None)],
                           buf)
    live_slot, stale_slot = 3, 7
    gen_live = np.asarray(buf.gen)[live_slot]
    # stale: stamped one generation behind the slot's current one
    dealer.queue_writeback(np.array([stale_slot]), np.array([9.0]),
                           np.array([np.asarray(buf.gen)[stale_slot] - 1]))
    dealer.queue_writeback(np.array([live_slot]), np.array([2.0]),
                           np.array([gen_live]))
    dealer.ingest_and_deal((), buf)  # idle tick settles the queue
    leaf = np.asarray(buf.trees.sum_tree)[buf.trees.capacity + live_slot]
    assert leaf == np.float32(2.0 ** 0.6)  # host pow, cast f32
    stale_leaf = np.asarray(
        buf.trees.sum_tree)[buf.trees.capacity + stale_slot]
    assert stale_leaf == np.float32(1.0)  # untouched commit priority
    assert dealer.writeback_dropped_stale == 1
    assert dealer.max_priority == pytest.approx(2.0)
    assert buf.max_priority == pytest.approx(2.0)


def test_device_ring_clear_deletes_dropped_blocks(rng):
    """DeviceDealtBlockRing.clear (the replica-kill path) must eagerly
    delete the dropped blocks' device buffers — dead sample HBM is
    reclaimed at the kill instant, not at the next GC cycle."""
    buf, ring, dealer = _device_rig(ring_cls=DeviceDealtBlockRing)
    dealer.publish(dealer.ingest_and_deal(
        [(buf.add(_mk_batch(rng, 16)), None, None)], buf))
    blocks = list(ring._q)
    assert blocks, "dealer never dealt"
    held = [a for blk in blocks
            for a in (*blk.batches, blk.weights, blk.idx, blk.gen)]
    assert ring.clear() == len(blocks)
    assert all(a.is_deleted() for a in held)
    # the buffer's own arrays must NOT be collateral damage
    assert not buf.trees.sum_tree.is_deleted()
    jax.block_until_ready(buf.storage.obs)


# ------------------------------------------------- runtime sentinels


def test_deal_dispatch_sentinels(rng):
    """The tentpole's transfer story, pinned: after warmup the
    ingest+deal loop must show ZERO recompiles, explicit H2D only for
    staged actor frames (never sampled rows), and the compiled deal
    dispatch must contain ZERO resharding collectives."""
    from d4pg_tpu.io.profiling import (RecompileSentinel, ReshardSentinel,
                                       TransferSentinel)

    buf, ring, dealer = _device_rig()
    feed = _mk_batch(rng, 16)
    dealer.publish(dealer.ingest_and_deal([(buf.add(feed), None, None)],
                                          buf))
    while ring.pop(timeout=0) is not None:
        pass
    rounds = 6
    with RecompileSentinel() as rec, TransferSentinel() as tr:
        for _ in range(rounds):
            dealer.publish(dealer.ingest_and_deal(
                [(buf.add(feed), None, None)], buf))
            while ring.pop(timeout=0) is not None:
                pass
        jax.block_until_ready(buf.trees.sum_tree)
    rec.assert_clean("device ingest+deal steady state")
    assert tr.h2d <= rounds, (
        f"{tr.h2d} explicit H2D over {rounds} ticks — sampled rows must "
        "never cross host->device")
    resh = ReshardSentinel()
    u = np.zeros((dealer.k, dealer.batch_size), np.float32)
    resh.inspect(dealer.deal_fn, buf.storage, buf.trees.sum_tree,
                 buf.trees.min_tree, buf.gen, u, np.int32(buf.size))
    resh.assert_clean("device deal dispatch")
    assert resh.steady_state_reshards == 0


# ----------------------------------------------- chaos smoke (device)


@pytest.mark.fleet
def test_device_sampler_chaos_smoke():
    """The device arm under the sampler fault set (consumer kill +
    stale-generation injection + sender chaos): every gating oracle
    holds and the broad top-frame containments never fire
    (contained_crashes delta 0)."""
    from d4pg_tpu.fleet.sampler_chaos import (SamplerChaosConfig,
                                              run_sampler_chaos)
    from d4pg_tpu.obs.registry import REGISTRY

    crashes0 = REGISTRY.counter("threads.contained_crashes").value
    rep = run_sampler_chaos(SamplerChaosConfig(
        sample_path="device", n_actors=4, duration_s=2.5,
        rows_per_sec=40.0, learner_kills=1, stale_frames=2, seed=5))
    assert REGISTRY.counter("threads.contained_crashes").value == crashes0
    assert rep["deadlocks"] == 0
    assert rep["hierarchy_violations"] == 0
    assert rep["trace_orphans"] == 0
    assert rep["sampler"]["dealt_dead_tickets"] == 0
    assert rep["consumer"]["sample_path_buffer_acqs"] == 0
    assert rep["consumer"]["consumer_kills"] == 1
    assert rep["ingest_shards"] == 1  # coerced: single commit thread
    assert rep["sampler"]["dealt_blocks"] > 0
    assert rep["consumer"]["blocks_consumed"] > 0


# ------------------------------------------- autotune arbitration


def test_select_sampler_policy_and_validation():
    from d4pg_tpu.ops import autotune as at

    r = at.select_sampler("auto", capacity=CAP, k=K, batch_size=B)
    if jax.default_backend() != "tpu":
        # off-accelerator the three-arm A/B shows per-deal dispatch
        # saturating the commit thread: auto falls back to the PR-12
        # host dealer, no timing pass
        assert r.selected == "host" and r.timings_ms is None
    assert at.select_sampler("scan", capacity=CAP, k=K,
                             batch_size=B).selected == "scan"
    with pytest.raises(ValueError, match="unknown --sampler arm"):
        at.select_sampler("einsum", capacity=CAP, k=K, batch_size=B)


def test_autotune_block_unified_schema():
    """Satellite contract: ONE schema-versioned ``autotune`` bench block
    carrying every arbitration surface's decision — projection AND
    sampler — each with (selected, reason, timings_ms)."""
    from d4pg_tpu.ops import autotune as at

    at.select_projection("einsum", batch_size=B, v_min=0.0, v_max=1.0,
                         n_atoms=11)
    at.select_sampler("scan", capacity=CAP, k=K, batch_size=B)
    blk = at.autotune_block()
    assert blk["metric"] == "autotune"
    assert blk["schema"] == at.AUTOTUNE_SCHEMA == 1
    for surface in ("projection", "sampler"):
        row = blk["surfaces"][surface]
        assert set(row) == {"selected", "reason", "timings_ms"}
        assert row["selected"]
    assert blk["surfaces"]["projection"]["selected"] == "einsum"
    assert blk["surfaces"]["sampler"]["selected"] == "scan"
