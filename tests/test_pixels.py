"""Pixel (conv-encoder) path: uint8 replay storage, PixelActor/Critic
through the jit'd update, and the full train driver on the fake pixel env
(the DM-Control-from-pixels capability, BASELINE.md config #4 — no
dm_control needed)."""

import jax
import numpy as np
import pytest

from d4pg_tpu.config import ExperimentConfig
from d4pg_tpu.envs import PixelPointEnv
from d4pg_tpu.learner import D4PGConfig, init_state, make_update
from d4pg_tpu.replay import NStepFolder, ReplayBuffer
from d4pg_tpu.replay.uniform import TransitionBatch

SHAPE = (16, 16, 3)


def test_pixel_buffer_uint8_storage(rng):
    buf = ReplayBuffer(100, SHAPE, 2)
    assert buf.obs.dtype == np.uint8
    n = 8
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        action=rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        reward=np.zeros(n, np.float32),
        next_obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )
    buf.add(batch)
    out = buf.sample(4)
    assert out.obs.shape == (4, *SHAPE) and out.obs.dtype == np.uint8


def test_pixel_nstep_folder(rng):
    f = NStepFolder(2, 0.9, num_envs=1, obs_dim=SHAPE, act_dim=2)
    for t in range(3):
        out = f.step(
            rng.integers(0, 255, (1, *SHAPE), dtype=np.uint8),
            rng.uniform(-1, 1, (1, 2)).astype(np.float32),
            np.array([1.0]),
            rng.integers(0, 255, (1, *SHAPE), dtype=np.uint8),
            np.array([False]),
        )
    assert out.obs.shape[0] == 1 and out.obs.dtype == np.uint8
    assert out.reward[0] == pytest.approx(1.0 + 0.9)


def test_pixel_learner_update(rng):
    config = D4PGConfig(
        obs_dim=int(np.prod(SHAPE)), act_dim=2, v_min=-20.0, v_max=0.0,
        n_atoms=11, hidden=(32, 32), pixels=True, obs_shape=SHAPE,
    )
    assert config.obs_spec == SHAPE
    state = init_state(config, jax.random.key(0))
    update = make_update(config, donate=False, use_is_weights=False)
    n = 8
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        action=rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )
    state, metrics = update(state, batch)
    assert np.isfinite(float(metrics["critic_loss"]))
    assert int(state.step) == 1


def test_pixel_train_end_to_end(tmp_path):
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="pixel-point", max_steps=10, num_envs=2, warmup=60, n_epochs=1,
        n_cycles=1, episodes_per_cycle=1, train_steps_per_cycle=2,
        eval_trials=1, batch_size=8, memory_size=500,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-20.0, v_max=0.0, n_steps=1,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])


def test_pixel_train_fused_device_replay(tmp_path):
    """uint8 frames through the fused path: device ring stores uint8, the
    in-scan gather feeds the conv encoder (which casts /255 itself), PER
    trees update from pixel TD errors."""
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="pixel-point", max_steps=10, num_envs=2, warmup=60, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=4,
        eval_trials=1, batch_size=8, memory_size=500,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-20.0, v_max=0.0, n_steps=1,
        replay_storage="device", fused_replay="on",
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])


def test_frame_stack_wrapper():
    """FrameStack: [H,W,C] -> [H,W,C*k], newest frame last, reset fills
    with k copies, uint8 preserved."""
    from d4pg_tpu.envs.fake import PixelPointEnv
    from d4pg_tpu.envs.wrappers import FrameStack

    env = FrameStack(PixelPointEnv(horizon=10, seed=0), 3)
    assert env.observation_space.shape == (16, 16, 9)
    obs, _ = env.reset()
    assert obs.shape == (16, 16, 9) and obs.dtype == np.uint8
    # reset: all three stacked frames identical
    np.testing.assert_array_equal(obs[..., :3], obs[..., 3:6])
    np.testing.assert_array_equal(obs[..., 3:6], obs[..., 6:9])
    prev = obs
    # a full-throttle action MOVES the blob, so the new frame differs from
    # the reset frame — otherwise the shift assertions below are vacuous
    obs2, *_ = env.step(np.ones(2, np.float32))
    # oldest two slots shift left; newest frame occupies the last slot
    np.testing.assert_array_equal(obs2[..., :3], prev[..., 3:6])
    np.testing.assert_array_equal(obs2[..., 3:6], prev[..., 6:9])
    assert not np.array_equal(obs2[..., 6:9], prev[..., 6:9])
    env.close()


def test_frame_stack_train_smoke(tmp_path):
    """--frame_stack 3 flows through dims/replay/encoder end to end."""
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.train import infer_dims, train

    cfg = ExperimentConfig(
        env="pixel-point", max_steps=10, num_envs=2, warmup=50, n_epochs=1,
        n_cycles=1, episodes_per_cycle=1, train_steps_per_cycle=2,
        eval_trials=1, batch_size=8, memory_size=500, log_dir=str(tmp_path),
        hidden=(16, 16), n_atoms=11, v_min=-5.0, v_max=0.0,
        encoder_width=8, frame_stack=3,
    )
    obs_dim, act_dim, obs_dtype = infer_dims(cfg)
    assert obs_dim == (16, 16, 9) and obs_dtype == np.uint8
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])


def test_shared_encoder_tie_and_detached_policy(rng):
    """--share_encoder (SAC-AE/DrQ): after every update the actor's
    encoder subtree is bitwise the critic's (trained by the critic loss
    alone), the policy gradient never moves it (actor Adam moments for
    the subtree stay exactly zero), and the actor MLP still trains."""
    config = D4PGConfig(
        obs_dim=int(np.prod(SHAPE)), act_dim=2, v_min=-20.0, v_max=0.0,
        n_atoms=11, hidden=(32, 32), pixels=True, obs_shape=SHAPE,
        encoder_channels=(8, 8, 8, 8), share_encoder=True,
    )
    state = init_state(config, jax.random.key(0))
    update = make_update(config, donate=False, use_is_weights=False)
    n = 8
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        action=rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )
    prev = state
    for _ in range(2):
        state, metrics = update(state, batch)
    tree = jax.tree_util.tree_leaves
    for a, c in zip(tree(state.actor_params["params"]["encoder"]),
                    tree(state.critic_params["params"]["encoder"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    # encoder DID train (via the critic loss), actor MLP DID train
    assert any(
        np.any(np.asarray(a) != np.asarray(b))
        for a, b in zip(tree(prev.critic_params["params"]["encoder"]),
                        tree(state.critic_params["params"]["encoder"])))
    assert any(
        np.any(np.asarray(a) != np.asarray(b))
        for a, b in zip(tree(prev.actor_params["params"]["actor"]),
                        tree(state.actor_params["params"]["actor"])))
    # the policy loss is detached from the encoder: its Adam moments for
    # the tied subtree are exactly zero after real update steps
    mu = state.actor_opt_state[0].mu["params"]["encoder"]
    assert all(np.all(np.asarray(x) == 0) for x in tree(mu))
    assert np.isfinite(float(metrics["actor_loss"]))


def test_shared_encoder_multi_update_donation(rng):
    """Regression (round 5): the tied encoder subtree must be a COPY, not
    an alias — an aliased buffer appears in both donated param trees of
    the K-scan update and XLA rejects donating the same buffer twice
    (--share_encoder + --updates_per_dispatch>1 crashed at dispatch)."""
    from d4pg_tpu.learner import make_multi_update

    config = D4PGConfig(
        obs_dim=int(np.prod(SHAPE)), act_dim=2, v_min=-20.0, v_max=0.0,
        n_atoms=11, hidden=(32, 32), pixels=True, obs_shape=SHAPE,
        encoder_channels=(8, 8, 8, 8), share_encoder=True,
    )
    state = init_state(config, jax.random.key(0))
    update = make_multi_update(config, donate=True, use_is_weights=False)
    k, n = 2, 8
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (k, n, *SHAPE), dtype=np.uint8),
        action=rng.uniform(-1, 1, (k, n, 2)).astype(np.float32),
        reward=rng.standard_normal((k, n)).astype(np.float32),
        next_obs=rng.integers(0, 255, (k, n, *SHAPE), dtype=np.uint8),
        done=np.zeros((k, n), np.float32),
        discount=np.full((k, n), 0.99, np.float32),
    )
    # two consecutive donated dispatches: the second consumes the first's
    # outputs as donated inputs — where aliased subtrees blow up
    for _ in range(2):
        state, metrics = update(state, batch)
    jax.block_until_ready(metrics["critic_loss"])
    assert np.isfinite(np.asarray(metrics["critic_loss"])).all()
    tree = jax.tree_util.tree_leaves
    for a, c in zip(tree(state.actor_params["params"]["encoder"]),
                    tree(state.critic_params["params"]["encoder"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_shared_encoder_tie_survives_warm_moments(rng):
    """Flipping --share_encoder ON over a resumed UNshared checkpoint
    leaves stale nonzero actor-Adam moments for the encoder subtree;
    those emit decaying updates for many steps. The tie is re-asserted
    after apply_updates, so the published actor encoder stays bitwise
    the critic's anyway."""
    kw = dict(
        obs_dim=int(np.prod(SHAPE)), act_dim=2, v_min=-20.0, v_max=0.0,
        n_atoms=11, hidden=(32, 32), pixels=True, obs_shape=SHAPE,
        encoder_channels=(8, 8, 8, 8),
    )
    n = 8
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        action=rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )
    # a few UNshared steps build nonzero encoder moments in the actor Adam
    unshared = D4PGConfig(**kw)
    state = init_state(unshared, jax.random.key(0))
    update = make_update(unshared, donate=False, use_is_weights=False)
    for _ in range(3):
        state, _ = update(state, batch)
    tree = jax.tree_util.tree_leaves
    mu = state.actor_opt_state[0].mu["params"]["encoder"]
    assert any(np.any(np.asarray(x) != 0) for x in tree(mu))
    # "resume" the same state with the flag flipped on
    shared = D4PGConfig(**kw, share_encoder=True)
    update_shared = make_update(shared, donate=False, use_is_weights=False)
    for _ in range(2):
        state, _ = update_shared(state, batch)
        # online AND target tie hold immediately after the flip — the
        # target tie must not be left to the (1-tau)^t soft-update decay
        for a, c in zip(tree(state.actor_params["params"]["encoder"]),
                        tree(state.critic_params["params"]["encoder"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        for a, c in zip(
                tree(state.target_actor_params["params"]["encoder"]),
                tree(state.target_critic_params["params"]["encoder"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_shared_encoder_requires_pixel_categorical():
    with pytest.raises(ValueError, match="share_encoder"):
        D4PGConfig(obs_dim=4, act_dim=2, share_encoder=True)


def test_shared_encoder_tied_from_init():
    """The tie holds from step 0 (targets included): a fresh shared init
    must not spend ~1/tau steps bootstrapping through a random unrelated
    actor encoder."""
    config = D4PGConfig(
        obs_dim=int(np.prod(SHAPE)), act_dim=2, v_min=-20.0, v_max=0.0,
        n_atoms=11, hidden=(32, 32), pixels=True, obs_shape=SHAPE,
        encoder_channels=(8, 8, 8, 8), share_encoder=True,
    )
    state = init_state(config, jax.random.key(0))
    tree = jax.tree_util.tree_leaves
    for params in (state.actor_params, state.target_actor_params):
        for a, c in zip(tree(params["params"]["encoder"]),
                        tree(state.critic_params["params"]["encoder"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
