"""Pixel (conv-encoder) path: uint8 replay storage, PixelActor/Critic
through the jit'd update, and the full train driver on the fake pixel env
(the DM-Control-from-pixels capability, BASELINE.md config #4 — no
dm_control needed)."""

import jax
import numpy as np
import pytest

from d4pg_tpu.config import ExperimentConfig
from d4pg_tpu.envs import PixelPointEnv
from d4pg_tpu.learner import D4PGConfig, init_state, make_update
from d4pg_tpu.replay import NStepFolder, ReplayBuffer
from d4pg_tpu.replay.uniform import TransitionBatch

SHAPE = (16, 16, 3)


def test_pixel_buffer_uint8_storage(rng):
    buf = ReplayBuffer(100, SHAPE, 2)
    assert buf.obs.dtype == np.uint8
    n = 8
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        action=rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        reward=np.zeros(n, np.float32),
        next_obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )
    buf.add(batch)
    out = buf.sample(4)
    assert out.obs.shape == (4, *SHAPE) and out.obs.dtype == np.uint8


def test_pixel_nstep_folder(rng):
    f = NStepFolder(2, 0.9, num_envs=1, obs_dim=SHAPE, act_dim=2)
    for t in range(3):
        out = f.step(
            rng.integers(0, 255, (1, *SHAPE), dtype=np.uint8),
            rng.uniform(-1, 1, (1, 2)).astype(np.float32),
            np.array([1.0]),
            rng.integers(0, 255, (1, *SHAPE), dtype=np.uint8),
            np.array([False]),
        )
    assert out.obs.shape[0] == 1 and out.obs.dtype == np.uint8
    assert out.reward[0] == pytest.approx(1.0 + 0.9)


def test_pixel_learner_update(rng):
    config = D4PGConfig(
        obs_dim=int(np.prod(SHAPE)), act_dim=2, v_min=-20.0, v_max=0.0,
        n_atoms=11, hidden=(32, 32), pixels=True, obs_shape=SHAPE,
    )
    assert config.obs_spec == SHAPE
    state = init_state(config, jax.random.key(0))
    update = make_update(config, donate=False, use_is_weights=False)
    n = 8
    batch = TransitionBatch(
        obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        action=rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.integers(0, 255, (n, *SHAPE), dtype=np.uint8),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )
    state, metrics = update(state, batch)
    assert np.isfinite(float(metrics["critic_loss"]))
    assert int(state.step) == 1


def test_pixel_train_end_to_end(tmp_path):
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="pixel-point", max_steps=10, num_envs=2, warmup=60, n_epochs=1,
        n_cycles=1, episodes_per_cycle=1, train_steps_per_cycle=2,
        eval_trials=1, batch_size=8, memory_size=500,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-20.0, v_max=0.0, n_steps=1,
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])


def test_pixel_train_fused_device_replay(tmp_path):
    """uint8 frames through the fused path: device ring stores uint8, the
    in-scan gather feeds the conv encoder (which casts /255 itself), PER
    trees update from pixel TD errors."""
    from d4pg_tpu.train import train

    cfg = ExperimentConfig(
        env="pixel-point", max_steps=10, num_envs=2, warmup=60, n_epochs=1,
        n_cycles=2, episodes_per_cycle=1, train_steps_per_cycle=4,
        eval_trials=1, batch_size=8, memory_size=500,
        log_dir=str(tmp_path), hidden=(16, 16), n_atoms=11,
        v_min=-20.0, v_max=0.0, n_steps=1,
        replay_storage="device", fused_replay="on",
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["critic_loss"])
